"""Compile & device-memory observatory: the engine's ledger of XLA
executables and device buffers.

Three subsystems grew independent compile budgets (whole-stage fusion,
device-side parquet decode, bounded_jit) because the XLA:CPU
many-executables cliff was hit blind; device memory was attributed to
nothing. This module unifies both resources behind one registry:

* **Program registry** — every jit entry point (bounded_jit wrappers,
  FusionProgramCache, DecodeProgramCache, the host-level jax.jit sites
  in relational.py/ops/) registers each compiled executable here with a
  structural signature split into named *facets* (mesh, dtype, shape,
  donation flag, ...), its source subsystem, compile wall, dispatch
  count and last-used stamp.

* **Retrace attribution** — a registration whose (subsystem, base)
  was seen before is a retrace; diffing the facet dicts names the
  cause (shape-bucket-churn, dtype-churn, mesh-change, donation-flag,
  weak-type-promotion, ...). A sliding-window storm detector flags a
  signature compiling repeatedly (telemetry sampler, /healthz, doctor).

* **Unified compile budget** — `BODO_TPU_XLA_MAX_EXECUTABLES` caps
  process-wide compiles; the legacy per-subsystem knobs
  (`BODO_TPU_FUSION_MAX_COMPILES`, `BODO_TPU_DEVICE_DECODE_MAX_COMPILES`)
  remain as sub-caps. Fusion and decode spend through `try_spend()`.

* **Device-buffer ledger** — `track_buffer`/`track_table` hook buffer
  creation (arrow ingest, fused-stage outputs, device decode) and a
  `weakref.finalize` per buffer hooks the free, attributing live device
  bytes to (query_id, operator). `verify_donation` proves a donated
  input was actually freed by the dispatch; `finish_query` runs the
  leak check at tracing.query_span() exit.

Import rules: stdlib only at module level — this module must be
importable from a /metrics scrape without dragging in jax. Consumers
that must never force *this* module to load read it via
`sys.modules.get` (metrics/telemetry/tracing); the jit call sites
import it directly (cheap).
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Dict, Optional, Tuple

# RLock: buffer finalizers can fire during gc triggered while this
# module already holds the lock on the same thread.
_lock = threading.RLock()


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except (TypeError, ValueError):
        return default


# ---------------------------------------------------------------------------
# enable toggle

_enabled = os.environ.get("BODO_TPU_XLA_OBSERVATORY", "1").lower() \
    not in ("0", "false", "off")


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Toggle registry + ledger accounting (budgets stay enforced)."""
    global _enabled
    with _lock:
        _enabled = bool(on)


# ---------------------------------------------------------------------------
# unified compile budget

# Legacy per-subsystem knobs survive as sub-caps; the unified pool
# defaults to their sum so default behavior is unchanged. <0 disables.
_SUB_CAPS: Dict[str, int] = {
    "fusion": _env_int("BODO_TPU_FUSION_MAX_COMPILES", 128),
    "device_decode": _env_int("BODO_TPU_DEVICE_DECODE_MAX_COMPILES", 64),
}


def _default_pool() -> int:
    caps = [c for c in _SUB_CAPS.values()]
    if any(c < 0 for c in caps):
        return -1  # any uncapped subsystem => pool uncapped by default
    return sum(caps)


_pool_cap = _env_int("BODO_TPU_XLA_MAX_EXECUTABLES", _default_pool())
_spent: Dict[str, int] = {}
_budget_denials: Dict[str, int] = {}


def try_spend(subsystem: str) -> bool:
    """Consume one unit of the unified compile budget for `subsystem`.

    Returns False when either the subsystem's legacy sub-cap or the
    unified `BODO_TPU_XLA_MAX_EXECUTABLES` pool is exhausted; the
    caller falls back (fusion -> unfused, decode -> host decode)."""
    with _lock:
        sub_cap = _SUB_CAPS.get(subsystem, -1)
        used = _spent.get(subsystem, 0)
        if sub_cap >= 0 and used >= sub_cap:
            _budget_denials[subsystem] = \
                _budget_denials.get(subsystem, 0) + 1
            return False
        if _pool_cap >= 0 and sum(_spent.values()) >= _pool_cap:
            _budget_denials[subsystem] = \
                _budget_denials.get(subsystem, 0) + 1
            return False
        _spent[subsystem] = used + 1
        return True


def reset_budget(subsystem: Optional[str] = None) -> None:
    """Return a subsystem's spend to the pool (its program cache was
    cleared, so its executables were released); None resets all."""
    with _lock:
        if subsystem is None:
            _spent.clear()
            _budget_denials.clear()
        else:
            _spent.pop(subsystem, None)
            _budget_denials.pop(subsystem, None)


def budget() -> dict:
    with _lock:
        spent = sum(_spent.values())
        return {
            "pool_cap": _pool_cap,
            "spent": spent,
            "remaining": (_pool_cap - spent) if _pool_cap >= 0 else -1,
            "per_subsystem": dict(_spent),
            "sub_caps": dict(_SUB_CAPS),
            "denials": dict(_budget_denials),
        }


def subsystem_budget_left(subsystem: str) -> int:
    """Units the subsystem could still spend (min of sub-cap and pool
    headroom); -1 when unlimited. Feeds legacy `budget_left` stats."""
    with _lock:
        sub_cap = _SUB_CAPS.get(subsystem, -1)
        used = _spent.get(subsystem, 0)
        heads = []
        if sub_cap >= 0:
            heads.append(max(0, sub_cap - used))
        if _pool_cap >= 0:
            heads.append(max(0, _pool_cap - sum(_spent.values())))
        return min(heads) if heads else -1


# ---------------------------------------------------------------------------
# program registry

_MAX_RECORDS = _env_int("BODO_TPU_XLA_MAX_RECORDS", 4096)

# retrace-cause taxonomy, checked in priority order: the first facet
# that differs names the cause.
_CAUSE_BY_FACET = (
    ("mesh", "mesh-change"),
    ("donate", "donation-flag"),
    ("weak_type", "weak-type-promotion"),
    ("dtype", "dtype-churn"),
    ("shape", "shape-bucket-churn"),
    ("dist", "distribution-change"),
    ("schema", "schema-change"),
    ("steps", "plan-change"),
    ("static", "static-arg-churn"),
    ("tree", "pytree-structure-change"),
)


class ProgramRecord:
    __slots__ = ("handle", "subsystem", "base", "facets", "compile_s",
                 "flops", "bytes_accessed", "dispatches", "created",
                 "last_used", "donated", "retrace_cause", "alive",
                 "progcheck")

    def __init__(self, handle: int, subsystem: str, base: str,
                 facets: Dict[str, Any], donated: bool,
                 retrace_cause: Optional[str]):
        self.handle = handle
        self.subsystem = subsystem
        self.base = base
        self.facets = facets
        self.compile_s = 0.0
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.dispatches = 0
        self.created = time.time()
        self.last_used = self.created
        self.donated = donated
        self.retrace_cause = retrace_cause
        self.alive = True
        self.progcheck = None  # verifier verdict (note_progcheck)

    def to_dict(self) -> dict:
        out = {
            "handle": self.handle, "subsystem": self.subsystem,
            "base": self.base,
            "facets": {k: repr(v)[:120] for k, v in self.facets.items()},
            "compile_s": round(self.compile_s, 6),
            "flops": self.flops, "bytes_accessed": self.bytes_accessed,
            "dispatches": self.dispatches,
            "created": self.created, "last_used": self.last_used,
            "donated": self.donated,
            "retrace_cause": self.retrace_cause, "alive": self.alive,
        }
        if self.progcheck is not None:
            out["progcheck"] = self.progcheck
        return out


_records: "OrderedDict[int, ProgramRecord]" = OrderedDict()
_latest_by_base: Dict[Tuple[str, str], int] = {}
_next_handle = 0
_retraces: Dict[str, int] = {}
_last_cause: Optional[str] = None
_totals = {"compiles": 0, "dispatches": 0, "evicted": 0,
           "compile_s": 0.0}

# recompile-storm detector: sliding window of compile events
_STORM_WINDOW_S = float(os.environ.get("BODO_TPU_XLA_STORM_WINDOW_S",
                                       "60"))
_STORM_THRESHOLD = _env_int("BODO_TPU_XLA_STORM_THRESHOLD", 8)
_compile_events: "deque[Tuple[float, Tuple[str, str]]]" = deque(
    maxlen=1024)


def _diff_cause(old: Dict[str, Any], new: Dict[str, Any]) -> str:
    for facet, cause in _CAUSE_BY_FACET:
        if old.get(facet) != new.get(facet):
            return cause
    for k in set(old) | set(new):
        if old.get(k) != new.get(k):
            return f"{k}-change"
    return "evicted-recompile"  # identical facets: prior was evicted


def register(subsystem: str, base: str,
             facets: Optional[Dict[str, Any]] = None, *,
             donated: bool = False) -> int:
    """Record one freshly compiled executable; returns a handle for
    touch()/note_compile()/mark_evicted(). Handle 0 = disabled."""
    global _next_handle, _last_cause
    if not _enabled:
        return 0
    facets = facets or {}
    with _lock:
        _next_handle += 1
        handle = _next_handle
        cause = None
        prev = _latest_by_base.get((subsystem, base))
        if prev is not None:
            prev_rec = _records.get(prev)
            if prev_rec is not None:
                cause = _diff_cause(prev_rec.facets, facets)
            else:
                cause = "evicted-recompile"
            _retraces[cause] = _retraces.get(cause, 0) + 1
            _last_cause = cause
        rec = ProgramRecord(handle, subsystem, base, facets, donated,
                            cause)
        _records[handle] = rec
        _latest_by_base[(subsystem, base)] = handle
        _totals["compiles"] += 1
        _compile_events.append((time.monotonic(), (subsystem, base)))
        while len(_records) > _MAX_RECORDS:
            _records.popitem(last=False)
        return handle


def touch(handle: int) -> None:
    """One dispatch of an already-registered executable."""
    if not handle or not _enabled:
        return
    with _lock:
        rec = _records.get(handle)
        if rec is not None:
            rec.dispatches += 1
            rec.last_used = time.time()
        _totals["dispatches"] += 1


def note_compile(handle: int, seconds: float) -> None:
    """Attribute measured compile wall to a registered executable."""
    with _lock:
        _totals["compile_s"] += float(seconds)
        rec = _records.get(handle)
        if rec is not None:
            rec.compile_s += float(seconds)


def note_progcheck(handle: int, info: dict) -> None:
    """Attach the static verifier's verdict (analysis/progcheck.py) to
    a registered executable: collective manifest, rank-invariance,
    static HBM peak, violations. Flows into registry dumps and
    flight-recorder bundles, where doctor's triage reads it."""
    if not handle:
        return
    with _lock:
        rec = _records.get(handle)
        if rec is not None:
            rec.progcheck = dict(info)


def note_cost(handle: int, flops: float = 0.0,
              bytes_accessed: float = 0.0) -> None:
    """Attach XLA cost-analysis numbers (best-effort; callers only
    compute them when BODO_TPU_XLA_COST_ANALYSIS is on)."""
    with _lock:
        rec = _records.get(handle)
        if rec is not None:
            rec.flops = float(flops)
            rec.bytes_accessed = float(bytes_accessed)


_COST_ANALYSIS = os.environ.get("BODO_TPU_XLA_COST_ANALYSIS", "0") \
    .lower() in ("1", "true", "on")


def cost_analysis_enabled() -> bool:
    return _COST_ANALYSIS


def mark_evicted(handle: int) -> None:
    """The owning cache dropped this executable (LRU/clear)."""
    if not handle:
        return
    with _lock:
        rec = _records.get(handle)
        if rec is not None and rec.alive:
            rec.alive = False
            _totals["evicted"] += 1


def storm() -> dict:
    """Sliding-window recompile-storm check: the hottest (subsystem,
    base) signature and whether it crossed the threshold."""
    now = time.monotonic()
    with _lock:
        while _compile_events and \
                now - _compile_events[0][0] > _STORM_WINDOW_S:
            _compile_events.popleft()
        counts: Dict[Tuple[str, str], int] = {}
        for _, sig in _compile_events:
            counts[sig] = counts.get(sig, 0) + 1
    if not counts:
        return {"storming": False, "signature": None,
                "compiles_in_window": 0,
                "window_s": _STORM_WINDOW_S,
                "threshold": _STORM_THRESHOLD}
    sig, n = max(counts.items(), key=lambda kv: kv[1])
    return {"storming": n >= _STORM_THRESHOLD,
            "signature": f"{sig[0]}:{sig[1]}", "compiles_in_window": n,
            "window_s": _STORM_WINDOW_S, "threshold": _STORM_THRESHOLD}


# ---------------------------------------------------------------------------
# facet extraction helpers (callers pass raw cache keys)

def _short(obj: Any) -> str:
    """Stable short fingerprint for a facet value too bulky to keep."""
    try:
        h = hash(obj)
    except TypeError:
        h = hash(repr(obj))
    return f"{h & 0xffffffff:08x}"


def _looks_schema(part: Any) -> bool:
    return (isinstance(part, tuple) and len(part) > 0
            and all(isinstance(p, tuple) and len(p) == 4
                    and isinstance(p[0], str) for p in part))


def _looks_mesh(part: Any) -> bool:
    return (isinstance(part, tuple) and len(part) == 2
            and isinstance(part[0], tuple) and len(part[0]) > 0
            and all(isinstance(d, int) for d in part[0])
            and isinstance(part[1], tuple)
            and all(isinstance(a, str) for a in part[1]))


def facets_from_sig(key: Any) -> Dict[str, Any]:
    """Best-effort facet split for a relational-style cache key: a
    tuple whose first element is the kind string, followed by schema
    tuples, "1D"/"REP" distribution markers, mesh keys and opaque
    static parts."""
    f: Dict[str, Any] = {}
    extras = []
    parts = key[1:] if isinstance(key, tuple) and key else ()
    for part in parts:
        if part in ("1D", "REP") and "dist" not in f:
            f["dist"] = part
        elif _looks_mesh(part) and "mesh" not in f:
            f["mesh"] = _short(part)
        elif _looks_schema(part) and "schema" not in f:
            f["schema"] = _short(part)
            f["dtype"] = tuple(p[1] for p in part)
        elif isinstance(part, bool) and "donate" not in f:
            f["donate"] = part
        else:
            extras.append(_short(part))
    if extras:
        f["static"] = tuple(extras)
    return f


def facets_from_leaves(struct: Any, leaf_keys: Tuple) -> Dict[str, Any]:
    """Facets for a bounded_jit key: ("a", shape, dtype) array leaves
    and ("v", value) static leaves."""
    shapes, dtypes, static = [], [], []
    for lk in leaf_keys:
        if lk and lk[0] == "a":
            shapes.append(lk[1])
            dtypes.append(lk[2])
        else:
            static.append(_short(lk[1:]))
    return {"shape": tuple(shapes), "dtype": tuple(dtypes),
            "static": tuple(static), "tree": _short(struct)}


# ---------------------------------------------------------------------------
# device-buffer ledger

_live: Dict[int, Tuple[int, Optional[str], str]] = {}  # id -> (nbytes, qid, op)
_ledger = {"created_bytes": 0, "freed_bytes": 0,
           "created_buffers": 0, "freed_buffers": 0,
           # high-water mark of live tracked bytes — what progcheck's
           # static HBM estimates are judged against (bench.py's
           # progcheck_hbm_estimate_ratio)
           "peak_live_bytes": 0}
_by_op: Dict[str, Dict[str, int]] = {}
_MAX_QUERY_REPORTS = 256
_by_query: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
_donation = {"verified": 0, "copied": 0}


def _query_entry(qid: Optional[str]) -> Dict[str, Any]:
    # callers hold _lock (track_buffer / finish_query critical sections)
    key = qid or "-"
    ent = _by_query.get(key)
    if ent is None:
        ent = {"created_bytes": 0, "freed_bytes": 0, "buffers": 0,
               "by_op": {}, "finished": False}
        # shardcheck: ignore[unlocked-shared-state]
        _by_query[key] = ent
        while len(_by_query) > _MAX_QUERY_REPORTS:
            # shardcheck: ignore[unlocked-shared-state]
            _by_query.popitem(last=False)
    return ent


def _current_qid() -> Optional[str]:
    tr = sys.modules.get("bodo_tpu.utils.tracing")
    if tr is not None:
        try:
            return tr.current_query_id()
        except Exception:
            return None
    return None


def _on_free(key: int) -> None:
    with _lock:
        ent = _live.pop(key, None)
        if ent is None:
            return
        nbytes, qid, op = ent
        _ledger["freed_bytes"] += nbytes
        _ledger["freed_buffers"] += 1
        ops = _by_op.get(op)
        if ops is not None:
            ops["freed_bytes"] += nbytes
            ops["live_buffers"] -= 1
        q = _by_query.get(qid or "-")
        if q is not None:
            q["freed_bytes"] += nbytes
            qo = q["by_op"].get(op)
            if qo is not None:
                qo["freed"] += nbytes


def track_buffer(arr: Any, op: str,
                 query_id: Optional[str] = None) -> bool:
    """Account one device buffer's creation to (query, operator); a
    weakref finalizer accounts the free. Tracers and non-weakrefable
    values are skipped. Returns True when tracked."""
    if not _enabled or arr is None:
        return False
    nbytes = getattr(arr, "nbytes", 0)
    if not isinstance(nbytes, int) or nbytes <= 0:
        return False
    # concrete device arrays only: tracers lack is_deleted
    if not hasattr(arr, "is_deleted"):
        return False
    key = id(arr)
    with _lock:
        if key in _live:
            return False
    try:
        weakref.finalize(arr, _on_free, key)
    except TypeError:
        return False
    qid = query_id if query_id is not None else _current_qid()
    with _lock:
        _live[key] = (nbytes, qid, op)
        _ledger["created_bytes"] += nbytes
        _ledger["created_buffers"] += 1
        live = _ledger["created_bytes"] - _ledger["freed_bytes"]
        if live > _ledger["peak_live_bytes"]:
            _ledger["peak_live_bytes"] = live
        ops = _by_op.setdefault(op, {"created_bytes": 0,
                                     "freed_bytes": 0,
                                     "live_buffers": 0})
        ops["created_bytes"] += nbytes
        ops["live_buffers"] += 1
        q = _query_entry(qid)
        q["created_bytes"] += nbytes
        q["buffers"] += 1
        q["by_op"].setdefault(op, {"created": 0, "freed": 0})
        q["by_op"][op]["created"] += nbytes
    return True


def track_table(t: Any, op: str,
                query_id: Optional[str] = None) -> int:
    """Track every column buffer (data + validity) of a Table."""
    if not _enabled or t is None:
        return 0
    n = 0
    try:
        cols = t.columns.values()
    except AttributeError:
        return 0
    for col in cols:
        if track_buffer(getattr(col, "data", None), op, query_id):
            n += 1
        if track_buffer(getattr(col, "valid", None), op, query_id):
            n += 1
    return n


def mark_deleted(arr: Any) -> None:
    """A dispatch donated this buffer: its device memory is gone even
    though the Python object survives. Accounts the free now; the
    later weakref finalizer becomes a no-op."""
    _on_free(id(arr))


def verify_donation(t: Any) -> bool:
    """After a donated dispatch, check the donated input's buffers were
    actually consumed by XLA (`is_deleted()`). Freed buffers are
    released from the ledger immediately; a False return means the
    runtime silently copied instead of donating."""
    deleted, total = 0, 0
    try:
        cols = list(t.columns.values())
    except AttributeError:
        cols = []
    for col in cols:
        for arr in (getattr(col, "data", None),
                    getattr(col, "valid", None)):
            if arr is None or not hasattr(arr, "is_deleted"):
                continue
            total += 1
            try:
                if arr.is_deleted():
                    deleted += 1
                    mark_deleted(arr)
            except Exception:
                pass
    ok = total > 0 and deleted == total
    with _lock:
        if ok:
            _donation["verified"] += 1
        else:
            _donation["copied"] += 1
    return ok


def live_bytes() -> int:
    with _lock:
        return _ledger["created_bytes"] - _ledger["freed_bytes"]


def finish_query(qid: Optional[str]) -> dict:
    """Leak check at query_span exit: per-query created/freed/live
    device bytes. `live` > 0 is *occupancy* (results the caller still
    holds), not necessarily a leak — the caller decides."""
    with _lock:
        ent = _by_query.get(qid or "-")
        if ent is None:
            return {"query_id": qid, "created_bytes": 0,
                    "freed_bytes": 0, "live_bytes": 0, "buffers": 0}
        ent["finished"] = True
        return {"query_id": qid,
                "created_bytes": ent["created_bytes"],
                "freed_bytes": ent["freed_bytes"],
                "live_bytes": ent["created_bytes"] - ent["freed_bytes"],
                "buffers": ent["buffers"],
                "by_op": {k: dict(v) for k, v in ent["by_op"].items()}}


def query_report(qid: Optional[str] = None) -> dict:
    return finish_query(qid) if qid else ledger_stats()


def leak_check(collect: bool = True) -> dict:
    """Force a gc pass (finalizers fire) and report what stayed live,
    grouped by op — the bench leak assertion and doctor's leak triage
    both read this."""
    if collect:
        gc.collect()
    with _lock:
        by_op: Dict[str, int] = {}
        for nbytes, _qid, op in _live.values():
            by_op[op] = by_op.get(op, 0) + nbytes
        return {"live_bytes": _ledger["created_bytes"]
                - _ledger["freed_bytes"],
                "live_buffers": len(_live),
                "by_op": dict(sorted(by_op.items(),
                                     key=lambda kv: -kv[1]))}


def ledger_stats() -> dict:
    with _lock:
        return {
            "created_bytes": _ledger["created_bytes"],
            "freed_bytes": _ledger["freed_bytes"],
            "live_bytes": _ledger["created_bytes"]
            - _ledger["freed_bytes"],
            "created_buffers": _ledger["created_buffers"],
            "freed_buffers": _ledger["freed_buffers"],
            "peak_live_bytes": _ledger["peak_live_bytes"],
            "live_buffers": len(_live),
            "by_op": {k: dict(v) for k, v in _by_op.items()},
            "donation": dict(_donation),
        }


# ---------------------------------------------------------------------------
# snapshots & dumps

def head() -> dict:
    """Cheap snapshot for per-node deltas (physical executor)."""
    with _lock:
        return {"compiles": _totals["compiles"],
                "dispatches": _totals["dispatches"],
                "retraces": sum(_retraces.values()),
                "last_cause": _last_cause,
                "live_bytes": _ledger["created_bytes"]
                - _ledger["freed_bytes"]}


def stats() -> dict:
    """Full summary: registry counts, retrace taxonomy, budget, storm
    state and the device ledger — what telemetry.sample() embeds."""
    with _lock:
        alive = sum(1 for r in _records.values() if r.alive)
        by_sub: Dict[str, Dict[str, Any]] = {}
        for r in _records.values():
            s = by_sub.setdefault(r.subsystem,
                                  {"executables": 0, "alive": 0,
                                   "compile_s": 0.0, "dispatches": 0})
            s["executables"] += 1
            s["alive"] += 1 if r.alive else 0
            s["compile_s"] += r.compile_s
            s["dispatches"] += r.dispatches
        pc_programs = pc_violations = 0
        for r in _records.values():
            if r.progcheck is not None:
                pc_programs += 1
                pc_violations += len(r.progcheck.get("violations", ()))
        summary = {
            "executables": len(_records), "alive": alive,
            "progcheck": {"programs": pc_programs,
                          "violations": pc_violations},
            "compiles": _totals["compiles"],
            "dispatches": _totals["dispatches"],
            "evicted": _totals["evicted"],
            "compile_s": round(_totals["compile_s"], 6),
            "retraces": dict(_retraces),
            "retraces_total": sum(_retraces.values()),
            "by_subsystem": {k: {**v,
                                 "compile_s": round(v["compile_s"], 6)}
                             for k, v in by_sub.items()},
        }
    summary["budget"] = budget()
    summary["storm"] = storm()
    summary["ledger"] = ledger_stats()
    return summary


def registry_dump(limit: Optional[int] = None) -> list:
    """Per-program records, most recent first (flight-recorder bundles
    embed this as xla_registry.json)."""
    with _lock:
        recs = [r.to_dict() for r in reversed(_records.values())]
    return recs[:limit] if limit else recs


def top_programs(n: int = 5, key: str = "compile_s") -> list:
    with _lock:
        recs = sorted(_records.values(),
                      key=lambda r: -getattr(r, key, 0.0))
        return [r.to_dict() for r in recs[:n]]


def reset() -> None:
    """Full teardown (runtests.py group teardown + test isolation)."""
    global _next_handle, _last_cause
    with _lock:
        _last_cause = None
        _records.clear()
        _latest_by_base.clear()
        _retraces.clear()
        _compile_events.clear()
        _next_handle = 0
        for k in _totals:
            _totals[k] = 0.0 if k == "compile_s" else 0
        _spent.clear()
        _budget_denials.clear()
        _live.clear()
        for k in _ledger:
            _ledger[k] = 0
        _by_op.clear()
        _by_query.clear()
        for k in _donation:
            _donation[k] = 0
