"""Table offload: device → pooled host buffers (spillable) → device.

The larger-than-HBM story (reference analogue: operator state spilling
through OperatorBufferPool/StorageManager, bodo/libs/_operator_pool.h,
_storage_manager.h:116): a Table's columns move into native pool buffers
on the host, become spillable to disk when unpinned, and restore to
device on demand. The executor can park build-side tables or partial
results here between pipeline stages.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.runtime.pool import HostBufferPool, PooledBuffer, default_pool
from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.table import Column, Table


@dataclass
class _OffCol:
    data: PooledBuffer
    data_dtype: np.dtype
    valid: Optional[PooledBuffer]
    dtype: dt.DType
    dictionary: Optional[np.ndarray]
    capacity: int


class OffloadedTable:
    """Host-resident, spill-capable snapshot of a Table."""

    def __init__(self, cols: Dict[str, _OffCol], nrows: int,
                 distribution: str, pool: HostBufferPool):
        self._cols = cols
        self._nrows = nrows
        self._distribution = distribution
        self._pool = pool
        self._closed = False

    def unpin(self) -> None:
        """Make all buffers spillable under memory pressure."""
        if self._closed:
            raise RuntimeError("OffloadedTable already restored/freed")
        for c in self._cols.values():
            c.data.unpin()
            if c.valid is not None:
                c.valid.unpin()

    def spill(self) -> int:
        """Force-spill all unpinned buffers; returns count spilled."""
        n = 0
        for c in self._cols.values():
            n += int(c.data.spill())
            if c.valid is not None:
                n += int(c.valid.spill())
        return n

    @property
    def nrows(self) -> int:
        return self._nrows

    def restore_slice(self, lo: int, hi: int,
                      unpin_after: bool = True) -> Table:
        """Rebuild a REP device Table from host rows [lo, hi) WITHOUT
        closing the offloaded table (the external sort/join restore
        spilled state one range at a time — reference analogue: partition
        rescan in bodo/libs/streaming/_sort.cpp /
        _join.h JoinPartition::FinalizeBuild). Buffers are re-pinned for
        the copy (restoring from disk if spilled) and unpinned again by
        default so the remaining rows stay spillable."""
        if self._closed:
            raise RuntimeError("OffloadedTable already restored/freed")
        lo = max(0, min(lo, self._nrows))
        hi = max(lo, min(hi, self._nrows))
        cols: Dict[str, Column] = {}
        for name, c in self._cols.items():
            if not c.data._pinned:
                c.data.pin()
            arr = np.array(c.data.as_array(c.data_dtype)[lo:hi], copy=True)
            valid = None
            if c.valid is not None:
                if not c.valid._pinned:
                    c.valid.pin()
                valid = jnp.asarray(np.array(
                    c.valid.as_array(np.bool_)[lo:hi], copy=True))
            cols[name] = Column(jnp.asarray(arr), valid, c.dtype,
                                c.dictionary)
            if unpin_after:
                c.data.unpin()
                if c.valid is not None:
                    c.valid.unpin()
        return Table(cols, hi - lo, "REP", None)

    def host_column(self, name: str) -> np.ndarray:
        """Host view copy of one column's live rows (pins for the read)."""
        if self._closed:
            raise RuntimeError("OffloadedTable already restored/freed")
        c = self._cols[name]
        if not c.data._pinned:
            c.data.pin()
        arr = np.array(c.data.as_array(c.data_dtype)[:self._nrows],
                       copy=True)
        c.data.unpin()
        return arr

    def restore(self) -> Table:
        """Pin (restoring from disk as needed) and rebuild the device
        Table, then release the host buffers. One-shot: the offloaded
        table is closed afterwards."""
        if self._closed:
            raise RuntimeError("OffloadedTable already restored/freed")
        cols: Dict[str, Column] = {}
        for name, c in self._cols.items():
            if not c.data._pinned:
                c.data.pin()
            arr = np.array(c.data.as_array(c.data_dtype)[:c.capacity],
                           copy=True)
            valid = None
            if c.valid is not None:
                if not c.valid._pinned:
                    c.valid.pin()
                valid = jnp.asarray(np.array(
                    c.valid.as_array(np.bool_)[:c.capacity], copy=True))
            cols[name] = Column(jnp.asarray(arr), valid, c.dtype,
                                c.dictionary)
        t = Table(cols, self._nrows, "REP", None)
        self.free()
        if self._distribution == "1D":
            t = t.shard()
        return t

    def free(self) -> None:
        for c in self._cols.values():
            c.data.free()
            if c.valid is not None:
                c.valid.free()
        self._cols = {}
        self._closed = True


def offload_table(t: Table, pool: Optional[HostBufferPool] = None,
                  unpin: bool = True) -> OffloadedTable:
    """Move a Table's data into native host pool buffers (device memory is
    released once JAX drops its references)."""
    pool = pool or default_pool()
    src = t.gather() if t.distribution == "1D" else t
    cols: Dict[str, _OffCol] = {}
    for name, c in src.columns.items():
        host = np.asarray(jax.device_get(c.data))
        buf = pool.allocate(host.nbytes)
        buf.as_array(host.dtype)[:] = host.ravel()
        vbuf = None
        if c.valid is not None:
            hv = np.asarray(jax.device_get(c.valid))
            vbuf = pool.allocate(max(hv.nbytes, 1))
            vbuf.as_array(np.bool_)[:len(hv)] = hv
        cols[name] = _OffCol(buf, host.dtype, vbuf, c.dtype, c.dictionary,
                             host.shape[0])
    ot = OffloadedTable(cols, t.nrows, t.distribution, pool)
    if unpin:
        ot.unpin()
    return ot
