"""Memory governor: auto-derived device budgets + operator admission.

Default-on analogue of the reference's OperatorComptroller over a
budget-enforcing BufferPool (reference: bodo/libs/_memory.h:632 BufferPool
with a real size limit, bodo/libs/memory_budget.py OperatorComptroller
negotiating per-operator budgets). Where the port previously activated
its spill machinery only when `stream_device_budget_mb` was hand-set
(default 0 = unbounded), the governor

  1. DERIVES a real device budget at mesh init: probe
     `device.memory_stats()` (`bytes_limit` - `bytes_in_use`) when the
     backend reports it, else a platform table (TPU HBM per chip by
     device_kind; CPU = a fraction of host RAM via os.sysconf), minus a
     configurable headroom fraction;
  2. runs ADMISSION CONTROL: state-materializing operators request a
     reservation (`admit()`) before allocating. The governor grants up
     to `mem_op_fraction` of the derived budget; when concurrent grants
     oversubscribe the budget it first QUEUES the request briefly
     (waiting for a release), then grants a reduced slice — which
     forces the operator into its partitioned/spill mode, the same
     paths that used to be opt-in;
  3. provides the OOM-RETRY envelope primitives: `is_oom()` recognizes
     XLA RESOURCE_EXHAUSTED, `handle_oom()` halves the fattest active
     grant and spills the largest parked state via the comptroller —
     the plan executor re-runs the failed stage against the shrunken
     grant (plan/physical.py);
  4. exposes OBSERVABILITY: per-operator granted/peak/spilled bytes for
     the tracing profile, bench JSON, and the chrome-trace `memory`
     section.

The legacy `stream_device_budget_mb` knob still wins when set (tests and
users that pin an explicit budget keep exact behavior); the governor is
what happens when nobody set it.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from bodo_tpu.config import config
from bodo_tpu.utils.logging import log

# TPU HBM per chip, bytes — used when memory_stats() is unavailable
# (older runtimes / some plugin backends). Keyed by device_kind prefix.
_TPU_HBM_BYTES = {
    "TPU v2": 8 << 30,
    "TPU v3": 16 << 30,
    "TPU v4": 32 << 30,
    "TPU v5 lite": 16 << 30,
    "TPU v5e": 16 << 30,
    "TPU v5": 95 << 30,    # v5p
    "TPU v6 lite": 32 << 30,
    "TPU v6e": 32 << 30,
}
_CPU_RAM_FRACTION = 0.25   # treat a quarter of host RAM as "device" memory
_ADMIT_TIMEOUT_S = 5.0     # max time a request queues before a forced grant
_MIN_GRANT = 16 << 20      # grants never shrink below this (forward progress)


def _host_ram_bytes() -> Optional[int]:
    try:
        return os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        return None


def _probe_device_budget() -> int:
    """Free bytes on one local device (the mesh is symmetric), 0 if
    nothing can be determined."""
    import jax
    try:
        dev = jax.local_devices()[0]
    except Exception:
        return 0
    stats = None
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if stats and stats.get("bytes_limit"):
        return max(0, int(stats["bytes_limit"])
                   - int(stats.get("bytes_in_use", 0)))
    kind = getattr(dev, "device_kind", "") or ""
    if dev.platform == "tpu":
        for prefix, hbm in sorted(_TPU_HBM_BYTES.items(),
                                  key=lambda kv: -len(kv[0])):
            if kind.startswith(prefix):
                return hbm
        return 16 << 30  # unknown TPU generation: conservative default
    # CPU (and unknown platforms): a fraction of host RAM, split across
    # the virtual devices sharing it
    ram = _host_ram_bytes()
    if not ram:
        return 0
    n_local = max(len(jax.local_devices()), 1)
    return int(ram * _CPU_RAM_FRACTION / n_local)


class OperatorGrant:
    """One operator's memory reservation. The operator treats `.budget`
    exactly like the old `stream_device_budget_mb` bytes: accumulate
    device state until it exceeds the grant, then park/spill."""

    def __init__(self, gov: "MemoryGovernor", name: str, budget: int):
        self.gov = gov
        self.name = name
        self.budget = int(budget)
        self.granted = int(budget)
        self.used = 0
        self.peak = 0
        self.spilled_bytes = 0
        self.n_spills = 0
        self._released = False

    def update(self, nbytes: int) -> None:
        """Record current device-resident state size."""
        self.used = int(nbytes)
        if self.used > self.peak:
            self.peak = self.used

    def over_budget(self, nbytes: int) -> bool:
        """True when `nbytes` of state exceeds this grant — the caller
        must park/spill (its governed response). Also tracks peak."""
        self.update(nbytes)
        return bool(self.budget) and nbytes > self.budget

    def record_spill(self, nbytes: int) -> None:
        self.spilled_bytes += int(nbytes)
        self.n_spills += 1
        self.used = 0

    def shrink(self) -> int:
        """Halve the grant (OOM response); returns the new budget."""
        self.budget = max(_MIN_GRANT, self.budget // 2)
        return self.budget

    def release(self) -> None:
        if not self._released:
            self._released = True
            self.gov._release(self)

    # context-manager form for whole-table reservations
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class MemoryGovernor:
    """Arbitrates the derived device budget across operators."""

    def __init__(self):
        self._mu = threading.Condition(threading.Lock())
        self._derived = 0          # post-headroom device budget, bytes
        self._derived_key = None   # (platform, n_local) the probe ran on
        self._probe_override: Optional[int] = None  # test hook
        self._grants: List[OperatorGrant] = []
        self.n_queued = 0
        self.n_oom_retries = 0

    # -- derivation ----------------------------------------------------------

    def set_probe_for_testing(self, nbytes: Optional[int]) -> None:
        """Test hook: pretend the device probe returned `nbytes` (None
        restores the real probe). Forces re-derivation."""
        with self._mu:
            self._probe_override = nbytes
            self._derived_key = None

    def derived_budget(self) -> int:
        """Per-device budget after headroom; re-derives when the local
        device set changes (mesh re-init)."""
        import jax
        try:
            key = (jax.default_backend(), len(jax.local_devices()))
        except Exception:
            key = None
        with self._mu:
            if key != self._derived_key:
                raw = (self._probe_override if self._probe_override
                       is not None else _probe_device_budget())
                headroom = min(max(config.mem_headroom_frac, 0.0), 0.9)
                self._derived = max(0, int(raw * (1.0 - headroom)))
                self._derived_key = key
                if self._derived:
                    log(1, f"memory governor: derived device budget "
                           f"{self._derived >> 20} MiB "
                           f"(probe {raw >> 20} MiB, headroom "
                           f"{headroom:.0%})")
            return self._derived

    def operator_budget(self) -> int:
        """Default per-operator slice of the derived budget."""
        frac = min(max(config.mem_op_fraction, 0.05), 1.0)
        return int(self.derived_budget() * frac)

    # -- admission -----------------------------------------------------------

    def admit(self, name: str, want: int = 0,
              wait: bool = True) -> OperatorGrant:
        """Reserve memory for an operator that materializes state.

        Grants min(want or the default per-operator slice, what's left
        unreserved). When active grants oversubscribe the budget the
        request queues (bounded wait for a release), then receives a
        reduced slice — small grants are how the governor forces an
        operator into partitioned/spill mode.

        ``wait=False`` never queues: an oversubscribed request gets the
        minimal grant immediately. I/O prefetch workers use this — a
        derated lookahead depth is the right pressure response there,
        not a stalled stream.
        """
        # explicit legacy budget wins: exact old behavior
        legacy = int(config.stream_device_budget_mb) << 20
        if legacy:
            g = OperatorGrant(self, name, legacy)
            with self._mu:
                self._grants.append(g)
            return g
        if not config.mem_governor:
            g = OperatorGrant(self, name, 0)  # 0 = unbounded (old default)
            with self._mu:
                self._grants.append(g)
            return g
        total = self.derived_budget()
        if not total:
            g = OperatorGrant(self, name, 0)
            with self._mu:
                self._grants.append(g)
            return g
        ask = min(int(want) or self.operator_budget(),
                  self.operator_budget())
        ask = max(ask, _MIN_GRANT)
        deadline = None
        with self._mu:
            while True:
                free = total - sum(g.budget for g in self._grants)
                if free >= ask or not self._grants:
                    budget = min(ask, max(free, _MIN_GRANT))
                    break
                if free >= _MIN_GRANT:
                    # reduced grant: operator runs, but parks/spills
                    # earlier — the governed response to pressure
                    budget = free
                    break
                if not wait:
                    budget = _MIN_GRANT
                    break
                import time as _time
                if deadline is None:
                    deadline = _time.monotonic() + _ADMIT_TIMEOUT_S
                    self.n_queued += 1
                    log(1, f"memory governor: {name} queued "
                           f"({ask >> 20} MiB asked, {free >> 20} MiB "
                           f"free)")
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    budget = _MIN_GRANT  # forced minimal grant: spill mode
                    break
                self._mu.wait(timeout=remaining)
            g = OperatorGrant(self, name, budget)
            self._grants.append(g)
        return g

    def resize_grant(self, grant: OperatorGrant, nbytes: int) -> None:
        """Retarget a persistent grant to its holder's current footprint
        (the result cache holds one long-lived grant sized to its device
        tier). Shrinking wakes queued admits — freed budget is real."""
        with self._mu:
            grant.budget = int(nbytes)
            if grant.budget > grant.granted:
                grant.granted = grant.budget
            grant.update(int(nbytes))
            self._mu.notify_all()

    def _release(self, grant: OperatorGrant) -> None:
        with self._mu:
            if grant in self._grants:
                self._grants.remove(grant)
            self._retired = getattr(self, "_retired", {})
            r = self._retired.setdefault(
                grant.name, {"granted": 0, "peak": 0, "spilled_bytes": 0,
                             "n_spills": 0, "count": 0})
            r["granted"] = max(r["granted"], grant.granted)
            r["peak"] = max(r["peak"], grant.peak)
            r["spilled_bytes"] += grant.spilled_bytes
            r["n_spills"] += grant.n_spills
            r["count"] += 1
            self._mu.notify_all()

    # -- OOM envelope --------------------------------------------------------

    @staticmethod
    def is_oom(exc: BaseException) -> bool:
        # taxonomy lives in the resilience layer so injected
        # RESOURCE_EXHAUSTED faults and real XLA OOMs classify the same
        from bodo_tpu.runtime.resilience import is_resource_exhausted
        return is_resource_exhausted(exc)

    def handle_oom(self, exc: BaseException) -> bool:
        """Shrink the fattest active grant and spill parked state so a
        stage re-run has room. Returns False when there is nothing left
        to shrink (re-raise)."""
        with self._mu:
            active = [g for g in self._grants if g.budget > _MIN_GRANT]
            victim = max(active, key=lambda g: g.budget, default=None)
        progress = False
        if victim is not None:
            old = victim.budget
            new = victim.shrink()
            log(1, f"memory governor: OOM — {victim.name} grant "
                   f"{old >> 20} -> {new >> 20} MiB")
            progress = True
        # shed the result cache's device tier (outside _mu — the cache
        # takes its own lock, then calls back into resize_grant): cached
        # results must never OOM a live query
        try:
            import sys as _sys
            rc = _sys.modules.get("bodo_tpu.runtime.result_cache")
            if rc is not None and rc.shed_for_pressure() > 0:
                progress = True
        except Exception:  # noqa: BLE001 - shedding is best-effort
            pass
        from bodo_tpu.runtime.comptroller import default_comptroller
        comp = default_comptroller()
        before = comp.n_spills
        try:
            comp.ensure_room(comp.limit)  # spill everything spillable
        except Exception:
            pass
        if comp.n_spills > before:
            progress = True
        if progress:
            self.n_oom_retries += 1
        return progress

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        derived = self.derived_budget() if config.mem_governor \
            else self._derived
        with self._mu:
            ops: Dict[str, dict] = {}
            for name, r in getattr(self, "_retired", {}).items():
                ops[name] = dict(r)
            for g in self._grants:
                r = ops.setdefault(
                    g.name, {"granted": 0, "peak": 0, "spilled_bytes": 0,
                             "n_spills": 0, "count": 0})
                r["granted"] = max(r["granted"], g.granted)
                r["peak"] = max(r["peak"], g.peak)
                r["spilled_bytes"] += g.spilled_bytes
                r["n_spills"] += g.n_spills
                r["count"] += 1
            return {
                "derived_budget_bytes": derived,
                "enabled": bool(config.mem_governor),
                "n_queued": self.n_queued,
                "n_oom_retries": self.n_oom_retries,
                "operators": ops,
            }


_governor: Optional[MemoryGovernor] = None
_gov_lock = threading.Lock()


def governor() -> MemoryGovernor:
    global _governor
    with _gov_lock:
        if _governor is None:
            _governor = MemoryGovernor()
        return _governor


def reset_governor() -> None:
    """Drop all state (tests)."""
    global _governor
    with _gov_lock:
        _governor = None


_res_depth = threading.local()


def reserve(name: str, nbytes: int):
    """Admission for a whole-table operator (join/sort/groupby in
    relational.py): reserve `nbytes` of the derived budget for the
    duration of the op. Outermost frame only — these operators re-enter
    each other (packed sort calls sort, right join calls left join) and
    nested reservations would double-count. Usable as a context
    manager; a no-op (yields None) when the governor is off, a legacy
    budget is pinned, or we're already inside a reservation."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        if (not config.mem_governor
                or int(config.stream_device_budget_mb)
                or getattr(_res_depth, "d", 0)):
            yield None
            return
        _res_depth.d = 1
        try:
            g = governor().admit(name, want=int(nbytes))
            g.update(int(nbytes))
            try:
                yield g
            finally:
                g.release()
        finally:
            _res_depth.d = 0
    return _cm()


def preadmission_charge(program: str):
    """Charge a compiled program's STATIC HBM peak estimate (the
    progcheck liveness sweep) against the governor for the duration of
    its dispatch. Pre-admission: when the budget is oversubscribed the
    dispatch queues (or runs under a reduced grant and the stage's
    OOM-retry envelope fires earlier) instead of discovering pressure
    via RESOURCE_EXHAUSTED mid-flight. A no-op context when progcheck
    has no estimate for the program, estimates are tiny, or the
    governor is off — and re-entrancy-safe like reserve()."""
    import contextlib
    import sys

    pc = sys.modules.get("bodo_tpu.analysis.progcheck")
    est = pc.hbm_estimate(program) if pc is not None else None
    if not est or est < _MIN_GRANT:
        return contextlib.nullcontext()
    return reserve(f"progcheck:{program}", int(est))


def table_device_bytes(t) -> int:
    """Device bytes of a Table's columns (data + validity)."""
    n = 0
    for c in t.columns.values():
        n += c.data.size * c.data.dtype.itemsize
        if c.valid is not None:
            n += c.valid.size
    return n
