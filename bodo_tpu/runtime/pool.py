"""ctypes bindings + on-demand build for the native host buffer pool."""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import tempfile
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "host_pool.cpp")
_LIB_PATH = os.path.join(_HERE, "_host_pool.so")

_lib = None
_lib_lock = threading.Lock()
HAS_NATIVE_POOL = False  # internal; use has_native_pool()


def has_native_pool() -> bool:
    """True when the native pool library is (or can be) loaded."""
    return _load() is not None


def _build() -> Optional[str]:
    """Compile the extension if needed (cached .so next to the source)."""
    if os.path.exists(_LIB_PATH) and \
            os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC):
        return _LIB_PATH
    cxx = os.environ.get("CXX", "g++")
    # build to a private temp path, then rename atomically — concurrent
    # first-time builders (spawned workers, pytest-xdist) must never load
    # a half-written .so
    tmp = _LIB_PATH + f".tmp.{os.getpid()}"
    cmd = [cxx, "-O2", "-fPIC", "-shared", "-std=c++17", _SRC, "-o", tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0:
            sys.stderr.write(
                f"[bodo_tpu] native pool build failed:\n"
                f"{r.stderr.decode()[:500]}\n")
            return None
        os.replace(tmp, _LIB_PATH)
        return _LIB_PATH
    except (OSError, subprocess.TimeoutExpired):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load():
    global _lib, HAS_NATIVE_POOL
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = _build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.btpu_pool_create.restype = ctypes.c_void_p
        lib.btpu_pool_create.argtypes = [ctypes.c_uint64, ctypes.c_char_p]
        lib.btpu_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.btpu_alloc.restype = ctypes.c_int64
        lib.btpu_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.POINTER(ctypes.c_void_p)]
        lib.btpu_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.btpu_free.restype = ctypes.c_int
        lib.btpu_pin.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                 ctypes.POINTER(ctypes.c_void_p)]
        lib.btpu_pin.restype = ctypes.c_int
        lib.btpu_unpin.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.btpu_unpin.restype = ctypes.c_int
        lib.btpu_spill.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.btpu_spill.restype = ctypes.c_int
        lib.btpu_stats.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_uint64 * 8)]
        _lib = lib
        HAS_NATIVE_POOL = True
        return lib


class PooledBuffer:
    """One pinned allocation; view it as numpy via .as_array(dtype, shape).
    unpin() makes it spillable; pin() restores (possibly from disk)."""

    def __init__(self, pool: "HostBufferPool", handle: int, nbytes: int,
                 ptr: int):
        self._pool = pool
        self._handle = handle
        self._nbytes = nbytes
        self._ptr = ptr
        self._pinned = True

    def as_array(self, dtype=np.uint8, shape=None) -> np.ndarray:
        """Zero-copy view of the pinned buffer.

        CONTRACT: views borrow the mapping — they dangle (use-after-unmap,
        SIGSEGV) once the buffer is unpinned+spilled or freed, and pin()
        may restore at a different address. Re-call as_array() after every
        pin(); never hold a view across unpin()/free()."""
        assert self._pinned, "buffer must be pinned to view"
        n = self._nbytes // np.dtype(dtype).itemsize
        buf = (ctypes.c_char * self._nbytes).from_address(self._ptr)
        arr = np.frombuffer(buf, dtype=dtype, count=n)
        return arr.reshape(shape) if shape is not None else arr

    def unpin(self) -> None:
        self._pool._lib.btpu_unpin(self._pool._pool, self._handle)
        self._pinned = False

    def pin(self) -> None:
        out = ctypes.c_void_p()
        rc = self._pool._lib.btpu_pin(self._pool._pool, self._handle,
                                      ctypes.byref(out))
        if rc != 0:
            raise MemoryError(f"pin failed ({rc})")
        self._ptr = out.value
        self._pinned = True

    def spill(self) -> bool:
        """Force-spill (must be unpinned). Returns True if spilled."""
        return self._pool._lib.btpu_spill(self._pool._pool,
                                          self._handle) == 0

    def free(self) -> None:
        if self._handle:
            self._pool._lib.btpu_free(self._pool._pool, self._handle)
            self._handle = 0


class HostBufferPool:
    """Python handle to the native pool (reference BufferPool surface:
    allocate/pin/unpin/spill + stats)."""

    def __init__(self, limit_bytes: int = 4 << 30,
                 spill_dir: Optional[str] = None):
        lib = _load()
        if lib is None:
            raise RuntimeError("native pool unavailable (no C++ toolchain)")
        self._lib = lib
        if spill_dir is None:
            spill_dir = tempfile.mkdtemp(prefix="bodo_tpu_spill_")
        os.makedirs(spill_dir, exist_ok=True)
        self._pool = lib.btpu_pool_create(limit_bytes, spill_dir.encode())
        self.spill_dir = spill_dir
        self.limit_bytes = limit_bytes

    def allocate(self, nbytes: int) -> PooledBuffer:
        out = ctypes.c_void_p()
        h = self._lib.btpu_alloc(self._pool, nbytes, ctypes.byref(out))
        if h == 0:
            raise MemoryError(f"pool allocation of {nbytes} bytes failed")
        return PooledBuffer(self, h, nbytes, out.value)

    def stats(self) -> dict:
        arr = (ctypes.c_uint64 * 8)()
        self._lib.btpu_stats(self._pool, ctypes.byref(arr))
        keys = ["bytes_allocated", "bytes_in_use", "bytes_spilled",
                "n_allocs", "n_spills", "n_restores",
                "n_overcommits", "bytes_over_limit"]
        return dict(zip(keys, [int(x) for x in arr]))

    def close(self) -> None:
        if self._pool:
            self._lib.btpu_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


_default: Optional[HostBufferPool] = None
_default_lock = threading.Lock()


def default_pool() -> HostBufferPool:
    """Shared process-wide pool. Double-checked under its own lock:
    spill/restore paths reach here from comptroller worker threads, and
    two racing first calls would each build (and leak) a native pool +
    spill directory."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = HostBufferPool()
    return _default
