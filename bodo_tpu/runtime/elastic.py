"""Elastic gangs: stage-checkpointed shrink-grow recovery.

The all-or-nothing fault story (retry the whole gang, or degrade one
stage to replicated) is intolerable once the gang is a long-lived
shared service: one lost rank kills every tenant's in-flight query and
cold-starts every cache. TPU fleet data treats rank loss and wedged
device tunnels as routine, and the SPMD answer to per-task lineage
recovery is recovery at the *stage*: checkpoint pipeline state at
stage boundaries, re-mesh onto the survivors, and resume the plan
suffix on the smaller mesh.

Three layers live here:

* :class:`CheckpointStore` — two-phase (register -> commit) per-rank
  stage snapshots. File tier: shards are pickled into the shared gang
  directory (``ckpt_e{epoch}_s{stage}_w{worker}.pkl``), written as
  ``.tmp`` and atomically renamed on commit, so a shard is either
  absent or complete — and the *dead* rank's last committed shard
  survives on shared storage, which is what makes N -> N-1 resharding
  possible without talking to the dead rank. Bounded: shards below the
  gang-wide committed frontier are pruned after every commit and the
  resident bytes are charged to the memory governor through one
  advisory grant. Metadata tier (no directory): in-process stage
  anchors for the serving path, where the semantic result cache
  already owns the bytes (its host-spill tier is the storage; the
  store tracks registration/commit accounting).

* :class:`StageRunner` + :func:`run_elastic` — the elastic gang.
  ``run_elastic(stages, n)`` launches n supervised workers (same
  machinery as ``spawn.run_spmd``); each worker checkpoints its state
  at every stage boundary, then barriers on its peers' checkpoints.
  When the parent detects a rank loss (returncode, stale heartbeat, or
  straggler attribution from the checkpoint frontier / lockstep
  arrival stamps) it writes a new mesh epoch to ``remesh.json``:
  survivors adopt contiguous new ranks, namespace their lockstep
  sequence numbers by the epoch, reshard the last *complete*
  checkpoint from N to N-1 shards, and resume the remaining stages on
  the smaller mesh. The recovery shuffle moves state through the
  shared gang directory, never through collectives — the CPU backend
  has no cross-process collectives, and a recovery path must not
  depend on the thing that just failed. A fresh ``jax.distributed``
  rendezvous on the new mesh is available behind
  ``config.elastic_remesh_distributed`` for real pods. A background
  grow path re-admits a replacement worker at the next stage boundary
  (and the serving layer restores full capacity at the next query
  boundary). If recovery *itself* fails — chaos-testable via the
  ``elastic.remesh`` / ``elastic.resume`` fault points — the gang
  falls back to the existing gang-level retry; it never wedges.

* Serving state — :func:`head` feeds the /healthz ``elastic`` block
  (mesh epoch, evicted workers, ``capacity_frac``) so the fleet
  admission twin can rescale quotas and routing for a shrunk gang;
  :func:`observe_stage` is the plan executor's stage-boundary hook;
  :class:`RankLost` + :func:`is_resumable` are the scheduler's
  resume-once contract (a resumed query re-runs only the plan suffix:
  completed stages come back from the result cache).
"""

from __future__ import annotations

import json
import os
import pickle
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import cloudpickle

from bodo_tpu.config import config
from bodo_tpu.runtime import resilience

_POLL_S = 0.05
_CKPT_RE = re.compile(r"^ckpt_e(\d+)_s(\d+)_w(\d+)\.pkl$")
REMESH_FILE = "remesh.json"
_EVICTED_SENTINEL = "__bodo_tpu_evicted__"


class RankLost(RuntimeError):
    """A gang rank was lost under an in-flight query. The scheduler
    treats this as resumable: the query thunk is re-run once, and the
    plan suffix past the last stage checkpoint is the only part that
    executes again (completed stages hit the result cache)."""

    def __init__(self, message: str = "gang rank lost mid-query",
                 evicted: Sequence[int] = (), epoch: int = 0):
        self.evicted = list(evicted)
        self.epoch = int(epoch)
        super().__init__(message)


class ElasticError(RuntimeError):
    """An elastic gang run failed beyond recovery. ``ranks`` carries
    the per-worker diagnostics (state "ok" / "dead" / "hung" /
    "evicted" / "killed"); ``recovery_failed`` is True when a re-mesh
    had been initiated (the failure happened during or after recovery)
    — the caller falls back to a whole-gang retry in that case."""

    def __init__(self, reason: str, ranks: Dict[int, dict],
                 transient: bool = False, recovery_failed: bool = False):
        self.reason = reason
        self.ranks = ranks
        self.transient = transient
        self.recovery_failed = recovery_failed
        lines = [f"elastic gang failed ({reason}):"]
        for i in sorted(ranks):
            d = ranks[i]
            line = f"  worker {i}: {d.get('state')}"
            if d.get("returncode") is not None:
                line += f" rc={d['returncode']}"
            lines.append(line)
        super().__init__("\n".join(lines))


def is_resumable(exc: BaseException) -> bool:
    """True when the scheduler may transparently re-run the query once
    (rank loss under an elastic gang, not a correctness error)."""
    if isinstance(exc, RankLost):
        return True
    # never resume lockstep divergence: that is a correctness bug
    if type(exc).__name__ == "LockstepError":
        return False
    return bool(getattr(exc, "rank_lost", False))


# --------------------------------------------------------------------
# checkpoint store
# --------------------------------------------------------------------

def default_merge(shards: List[object]) -> object:
    """Deterministic N-shard combine for the recovery shuffle (and for
    comparing a shrunk run against a clean one). Supports the shard
    shapes the executors move: pandas DataFrames (row concat), lists
    (concat), None."""
    if all(s is None for s in shards):
        return None
    try:
        import pandas as pd
    except Exception:  # pragma: no cover
        pd = None
    if pd is not None and all(isinstance(s, pd.DataFrame) for s in shards):
        return pd.concat(list(shards), ignore_index=True)
    if all(isinstance(s, list) for s in shards):
        return [x for s in shards for x in s]
    raise TypeError(
        "elastic.default_merge: unsupported shard type "
        f"{type(shards[0]).__name__}; pass merge=/split= to run_elastic")


def default_split(whole: object, k: int) -> List[object]:
    """Contiguous split of a merged state into k shards (inverse of
    :func:`default_merge` up to shard boundaries)."""
    if whole is None:
        return [None] * k
    try:
        import pandas as pd
    except Exception:  # pragma: no cover
        pd = None
    if pd is not None and isinstance(whole, pd.DataFrame):
        n = len(whole)
        bounds = [round(i * n / k) for i in range(k + 1)]
        return [whole.iloc[bounds[i]:bounds[i + 1]].reset_index(drop=True)
                for i in range(k)]
    if isinstance(whole, list):
        n = len(whole)
        bounds = [round(i * n / k) for i in range(k + 1)]
        return [whole[bounds[i]:bounds[i + 1]] for i in range(k)]
    raise TypeError(
        "elastic.default_split: unsupported state type "
        f"{type(whole).__name__}; pass merge=/split= to run_elastic")


class CheckpointStore:
    """Two-phase stage-checkpoint store (see module docstring).

    ``register`` stages the snapshot (a ``.tmp`` write in the file
    tier); ``commit`` makes it visible atomically. Nothing
    side-effecting belongs between the two — a resumed suffix would
    replay it (the ``checkpoint-non-idempotent`` shardcheck rule
    enforces this package-wide)."""

    def __init__(self, dirpath: Optional[str] = None,
                 budget_bytes: Optional[int] = None):
        self.dir = dirpath or None
        self.budget_bytes = int(budget_bytes if budget_bytes is not None
                                else (256 << 20))
        self._mu = threading.Lock()
        self._bytes = 0
        self._grant = None
        self._stats = {"registered": 0, "committed": 0, "pruned": 0,
                       "over_budget": 0}

    # -- two-phase write ----------------------------------------------
    def register(self, stage: int, epoch: int, worker: int,
                 state: object = None, meta: Optional[dict] = None) -> dict:
        """Stage a checkpoint of `state` entering `stage`. Returns the
        token `commit` consumes. File tier: pickles to ``.tmp`` now, so
        commit is a pure rename."""
        tok = {"stage": int(stage), "epoch": int(epoch),
               "worker": int(worker), "meta": meta or {}, "bytes": 0}
        if self.dir:
            final = os.path.join(
                self.dir, f"ckpt_e{epoch}_s{stage}_w{worker}.pkl")
            tmp = final + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(state, f)
            tok["path"], tok["tmp"] = final, tmp
            tok["bytes"] = os.path.getsize(tmp)
        else:
            tok["bytes"] = int((meta or {}).get("bytes", 0))
        with self._mu:
            self._stats["registered"] += 1
        return tok

    def commit(self, token: dict) -> Optional[str]:
        """Atomically publish a registered checkpoint."""
        path = None
        if self.dir and "tmp" in token:
            os.replace(token["tmp"], token["path"])
            path = token["path"]
        with self._mu:
            self._stats["committed"] += 1
            self._bytes += int(token.get("bytes", 0))
            if self._bytes > self.budget_bytes:
                self._stats["over_budget"] += 1
        self._sync_grant()
        return path

    def _sync_grant(self) -> None:
        # one advisory governor grant sized to the resident checkpoint
        # bytes — same pattern as the result cache's persistent grant.
        # Metadata-only stores (no file tier) never hold bytes of their
        # own — the result cache already charged the governor for the
        # anchored stage outputs — so charging again here would
        # double-count every stage boundary of every query.
        try:
            if not self.dir or not config.mem_governor:
                return
            from bodo_tpu.runtime import memory_governor as mg
            gov = mg.governor()
            with self._mu:
                nbytes = self._bytes
                if self._grant is None:
                    self._grant = gov.admit("elastic_ckpt", want=nbytes,
                                            wait=False)
            gov.resize_grant(self._grant, nbytes)
        except Exception:  # noqa: BLE001 - accounting never fails a ckpt
            pass

    # -- reads ---------------------------------------------------------
    def scan(self) -> Dict[tuple, set]:
        """Committed shards on disk: ``{(epoch, worker): {stages}}``."""
        out: Dict[tuple, set] = {}
        if not self.dir:
            return out
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            m = _CKPT_RE.match(name)
            if m:
                e, s, w = int(m.group(1)), int(m.group(2)), int(m.group(3))
                out.setdefault((e, w), set()).add(s)
        return out

    def complete_stage(self, epoch: int,
                       workers: Sequence[int]) -> Optional[int]:
        """Highest stage committed by EVERY worker of `epoch` (the
        resume point a re-mesh reshards from), or None."""
        sc = self.scan()
        common = None
        for w in workers:
            stages = sc.get((int(epoch), int(w)), set())
            common = stages if common is None else (common & stages)
            if not common:
                return None
        return max(common) if common else None

    def load(self, epoch: int, stage: int, worker: int) -> object:
        path = os.path.join(
            self.dir, f"ckpt_e{epoch}_s{stage}_w{worker}.pkl")
        with open(path, "rb") as f:
            return pickle.load(f)

    def reshard(self, epoch: int, stage: int,
                workers_in_rank_order: Sequence[int], new_n: int,
                merge: Callable, split: Callable) -> List[object]:
        """The recovery shuffle: read every old-mesh shard of one
        complete checkpoint (the dead rank's included — its file is on
        shared storage) in old mesh-rank order, combine, and re-split
        contiguously into `new_n` shards."""
        shards = [self.load(epoch, stage, w) for w in workers_in_rank_order]
        return split(merge(shards), new_n)

    # -- retention -----------------------------------------------------
    def prune(self, epoch: int, worker: int, keep_from_stage: int) -> None:
        """Drop this worker's shards of `epoch` below the gang-wide
        committed frontier. Never called with a frontier above the last
        complete stage, so the resume point always survives."""
        if not self.dir:
            return
        sc = self.scan()
        for s in sorted(sc.get((int(epoch), int(worker)), set())):
            if s < int(keep_from_stage):
                self._drop(epoch, s, worker)

    def prune_epochs_below(self, epoch: int, worker: int) -> None:
        """Drop this worker's shards of superseded mesh epochs (called
        once the current epoch has a complete checkpoint)."""
        if not self.dir:
            return
        sc = self.scan()
        for (e, w), stages in sc.items():
            if w == int(worker) and e < int(epoch):
                for s in stages:
                    self._drop(e, s, w)

    def _drop(self, epoch: int, stage: int, worker: int) -> None:
        path = os.path.join(
            self.dir, f"ckpt_e{epoch}_s{stage}_w{worker}.pkl")
        try:
            nbytes = os.path.getsize(path)
            os.remove(path)
        except OSError:
            return
        with self._mu:
            self._stats["pruned"] += 1
            self._bytes = max(0, self._bytes - nbytes)
        self._sync_grant()

    def stats(self) -> dict:
        with self._mu:
            d = dict(self._stats)
            d["bytes"] = self._bytes
            d["budget_bytes"] = self.budget_bytes
        return d


# --------------------------------------------------------------------
# worker side
# --------------------------------------------------------------------

class _Remesh(Exception):
    def __init__(self, doc: dict):
        self.doc = doc


class _Evicted(Exception):
    pass


class _Ctx:
    """Per-stage execution context handed to stage callables."""

    def __init__(self, rank, nprocs, stage, epoch, worker):
        self.rank = rank
        self.nprocs = nprocs
        self.stage = stage
        self.epoch = epoch
        self.worker = worker


def _read_remesh(d: str) -> Optional[dict]:
    try:
        with open(os.path.join(d, REMESH_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_remesh(d: str, doc: dict) -> None:
    tmp = os.path.join(d, REMESH_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, os.path.join(d, REMESH_FILE))


class StageRunner:
    """Worker half of an elastic gang: runs the stage list, snapshots
    state at every stage boundary, barriers on peers' checkpoints, and
    adopts mesh-epoch bumps (shrink, grow, or its own eviction) posted
    by the supervising parent."""

    def __init__(self, stages: Sequence[Callable], init=None, merge=None,
                 split=None, timeout: float = 180.0):
        self.stages = list(stages)
        self.init = init
        self.merge = merge or default_merge
        self.split = split or default_split
        self.dir = os.environ.get("BODO_TPU_ELASTIC_DIR") or \
            config.elastic_dir
        if not self.dir:
            raise RuntimeError("StageRunner needs a shared elastic dir "
                               "(BODO_TPU_ELASTIC_DIR)")
        self.worker = int(os.environ.get(
            "BODO_TPU_ELASTIC_WORKER",
            os.environ.get("BODO_TPU_PROC_ID", "0")))
        self.joiner = os.environ.get("BODO_TPU_ELASTIC_JOINER") == "1"
        self.deadline = time.monotonic() + float(timeout)
        self.store = CheckpointStore(
            self.dir, budget_bytes=config.elastic_ckpt_bytes)
        self.epoch = 0
        self.rank = int(os.environ.get("BODO_TPU_PROC_ID", "0"))
        self.nprocs = int(os.environ.get("BODO_TPU_NPROCS", "1"))
        # worker ids active in the current epoch, in mesh-rank order
        self.workers = list(range(self.nprocs))

    # -- protocol ------------------------------------------------------
    def run(self) -> object:
        try:
            if self.joiner:
                state, s = self._join()
            else:
                state = self.init(self.rank, self.nprocs) \
                    if self.init is not None else None
                s = 0
            while s < len(self.stages):
                try:
                    self._checkpoint(s, state)
                    self._await_stage(s)
                    state = self.stages[s](
                        state, _Ctx(self.rank, self.nprocs, s, self.epoch,
                                    self.worker))
                    s += 1
                except _Remesh as rm:
                    state = self._adopt(rm.doc)
                    s = int(rm.doc["resume_stage"])
            return state
        except _Evicted:
            self._mark_evicted()
            return _EVICTED_SENTINEL

    def _checkpoint(self, s: int, state: object) -> None:
        resilience.maybe_inject("elastic.checkpoint")
        self._poll_remesh()
        tok = self.store.register(stage=s, epoch=self.epoch,
                                  worker=self.worker, state=state)
        self.store.commit(tok)
        # retention: prune below the gang-wide committed frontier (the
        # slowest peer's newest stage), and superseded epochs once the
        # new mesh has a complete checkpoint of its own
        frontier = self.store.complete_stage(self.epoch, self.workers)
        if frontier is not None:
            self.store.prune(self.epoch, self.worker, frontier)
            if self.epoch > 0:
                self.store.prune_epochs_below(self.epoch, self.worker)

    def _await_stage(self, s: int) -> None:
        """Barrier: every current-epoch peer has committed stage `s`
        (or a re-mesh supersedes the wait)."""
        sc = self.store.scan()
        while not all(s in sc.get((self.epoch, w), ())
                      for w in self.workers):
            self._poll_remesh()
            if time.monotonic() > self.deadline:
                raise RuntimeError(
                    f"elastic: stage {s} barrier timed out at epoch "
                    f"{self.epoch} (worker {self.worker})")
            time.sleep(_POLL_S)
            sc = self.store.scan()

    def _poll_remesh(self) -> None:
        doc = _read_remesh(self.dir)
        if doc is None or int(doc.get("epoch", 0)) <= self.epoch:
            return
        if self.worker in [int(w) for w in doc.get("evicted", [])] or \
                str(self.worker) not in doc.get("workers", {}):
            raise _Evicted()
        raise _Remesh(doc)

    def _adopt(self, doc: dict) -> object:
        """Re-mesh: adopt the new epoch's contiguous rank, namespace
        lockstep by the epoch, optionally rendezvous a fresh
        jax.distributed cluster, and reshard the last complete
        checkpoint onto the new mesh."""
        # fault points fire under the OLD identity so `@rank` targeting
        # in BODO_TPU_FAULTS refers to pre-shrink ranks
        resilience.maybe_inject("elastic.remesh")
        self.epoch = int(doc["epoch"])
        ranks = {int(w): int(r) for w, r in doc["workers"].items()}
        self.workers = sorted(ranks, key=lambda w: ranks[w])
        self.rank = ranks[self.worker]
        self.nprocs = len(self.workers)
        os.environ["BODO_TPU_PROC_ID"] = str(self.rank)
        os.environ["BODO_TPU_NPROCS"] = str(self.nprocs)
        try:
            from bodo_tpu.analysis import lockstep
            lockstep.set_mesh_epoch(self.epoch, rank=self.rank,
                                    nprocs=self.nprocs)
        except Exception:  # pragma: no cover
            pass
        if config.elastic_remesh_distributed and doc.get("coord"):
            self._reinit_distributed(doc["coord"])
        resilience.maybe_inject("elastic.resume")
        prev_workers = [int(w) for w in doc["prev_workers"]]
        state = self.store.reshard(
            int(doc["prev_epoch"]), int(doc["resume_stage"]), prev_workers,
            self.nprocs, self.merge, self.split)[self.rank]
        return state

    def _reinit_distributed(self, coord: str) -> None:
        # best-effort: the host-file recovery path above is the one the
        # chaos bar depends on; a real pod re-forms the jax cluster
        # here so post-recovery collectives run on the new mesh
        try:
            import jax
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=self.nprocs,
                process_id=self.rank)
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(
                f"bodo_tpu.elastic: jax.distributed re-init skipped "
                f"({e})\n")

    def _join(self):
        """Grow path: a replacement worker waits for the mesh epoch
        that includes it, then enters through the same adoption/reshard
        path as a surviving rank."""
        while True:
            doc = _read_remesh(self.dir)
            if doc is not None and str(self.worker) in \
                    doc.get("workers", {}):
                return self._adopt(doc), int(doc["resume_stage"])
            if time.monotonic() > self.deadline:
                raise RuntimeError(
                    f"elastic: joiner {self.worker} never saw its mesh "
                    f"epoch")
            time.sleep(_POLL_S)

    def _mark_evicted(self) -> None:
        # clean shrink-eviction exit: the marker is how spawn
        # supervision and /healthz distinguish "evicted" from "died"
        path = os.path.join(self.dir, f"evicted_{self.worker}")
        try:
            with open(path, "w") as f:
                json.dump({"worker": self.worker, "epoch": self.epoch,
                           "ts": time.time()}, f)
        except OSError:  # pragma: no cover
            pass


def _elastic_entry(stages, init, merge, split, timeout):
    def entry(_process_index: int) -> object:
        runner = StageRunner(stages, init=init, merge=merge, split=split,
                             timeout=timeout)
        return runner.run()
    return entry


# --------------------------------------------------------------------
# parent side
# --------------------------------------------------------------------

class ElasticRun:
    """Result of :func:`run_elastic`: per-rank final states (final
    mesh-rank order) + a recovery report (epochs, evictions, MTTR)."""

    def __init__(self, results: List[object], report: dict):
        self.results = results
        self.report = report


def run_elastic(stages: Sequence[Callable], n_processes: int = 2, *,
                init: Optional[Callable] = None,
                merge: Optional[Callable] = None,
                split: Optional[Callable] = None,
                timeout: float = 180.0,
                grow: Optional[bool] = None) -> ElasticRun:
    """Run a stage pipeline across an elastic gang of `n_processes`.

    `stages` is a list of picklable ``fn(state, ctx) -> state`` shard
    transforms; `init(rank, nprocs)` builds each rank's initial shard.
    On rank loss the gang shrinks and resumes from the last complete
    stage checkpoint instead of failing (see module docstring); when
    elastic recovery itself cannot proceed, falls back to the
    gang-level retry (``config.elastic_gang_retries``) and raises
    :class:`ElasticError` only after that."""
    retries = max(0, int(config.elastic_gang_retries))
    attempt = 0
    while True:
        try:
            return _run_elastic_gang(stages, n_processes, init, merge,
                                     split, timeout, grow)
        except ElasticError as e:
            if attempt >= retries or \
                    not (e.recovery_failed or e.transient):
                raise
            attempt += 1
            resilience.count_gang_retry()
            sys.stderr.write(
                f"bodo_tpu.elastic: recovery failed ({e.reason}); "
                f"falling back to gang-level retry {attempt}\n")


class _Worker:
    def __init__(self, wid, proc, out, err, hb):
        self.wid = wid
        self.proc = proc
        self.out = out
        self.err = err
        self.hb = hb
        self.evicted = False


def _run_elastic_gang(stages, n_processes, init, merge, split, timeout,
                      grow) -> ElasticRun:
    from bodo_tpu import spawn

    hb_timeout = resilience._cfg("spawn_hb_timeout_s",
                                 "BODO_TPU_SPAWN_HB_TIMEOUT", 15.0, float)
    grow = config.elastic_grow if grow is None else bool(grow)
    max_shrinks = max(0, int(config.elastic_max_shrinks))
    min_ranks = max(1, int(config.elastic_min_ranks))
    straggler_s = float(config.elastic_straggler_s)
    resil_path = os.path.join(
        os.path.dirname(os.path.abspath(spawn.__file__)),
        "runtime", "resilience.py")
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(spawn.__file__)))
    entry = _elastic_entry(list(stages), init, merge, split, timeout)

    with tempfile.TemporaryDirectory(prefix="bodo_tpu_elastic_") as d:
        payload = os.path.join(d, "fn.pkl")
        with open(payload, "wb") as f:
            cloudpickle.dump(entry, f)
        worker_py = os.path.join(d, "worker.py")
        with open(worker_py, "w") as f:
            f.write(spawn._WORKER_CODE)
        coord = f"127.0.0.1:{spawn._free_port()}"
        store = CheckpointStore(d)
        workers: Dict[int, _Worker] = {}
        handles: List[object] = []

        def launch(wid: int, env_extra: Dict[str, str],
                   nprocs_env: int, proc_id: int) -> None:
            out = os.path.join(d, f"out_{wid}.pkl")
            err = os.path.join(d, f"err_{wid}.log")
            hb = os.path.join(d, f"hb_{wid}")
            env = spawn._worker_env(d, proc_id, nprocs_env, coord,
                                    resil_path, pkg_root, hb)
            env.update({"BODO_TPU_ELASTIC_DIR": d,
                        "BODO_TPU_ELASTIC_WORKER": str(wid)})
            if not config.elastic_remesh_distributed:
                # host-file recovery: each worker runs local jax; a
                # shared coordination service would fatally terminate
                # survivors ~100s after the very rank loss we recover
                # from (see spawn._WORKER_CODE)
                env["BODO_TPU_NO_JAX_DIST"] = "1"
            env.update(env_extra)
            ef = open(err, "wb")
            of = open(os.path.join(d, f"stdout_{wid}.log"), "wb")
            handles.extend([ef, of])
            proc = subprocess.Popen(
                [sys.executable, worker_py, payload, out],
                env=env, stdout=of, stderr=ef)
            workers[wid] = _Worker(wid, proc, out, err, hb)

        rank_of = {w: w for w in range(n_processes)}
        epoch = 0
        shrinks = grows = 0
        detect_ts: Optional[float] = None
        evicted_info: Dict[int, str] = {}
        recovery_initiated = False
        frontier_seen: Dict[int, tuple] = {}
        start = time.monotonic()
        deadline = start + float(timeout)

        def active() -> List[int]:
            return [w for w in sorted(workers) if not workers[w].evicted]

        def diag(reason: Optional[str], failing: set) -> Dict[int, dict]:
            out: Dict[int, dict] = {}
            for wid in sorted(workers):
                w = workers[wid]
                rc = w.proc.poll()
                if w.evicted or os.path.exists(
                        os.path.join(d, f"evicted_{wid}")):
                    state = "evicted"
                elif wid in failing:
                    state = ("hung" if reason == "hung worker" else
                             "timeout" if reason == "gang timeout" else
                             "dead")
                elif rc == 0:
                    state = "ok"
                elif rc is None:
                    state = "running"
                else:
                    state = "killed"
                e = {"state": state, "returncode": rc}
                if state == "evicted" and wid in evicted_info:
                    e["evicted_reason"] = evicted_info[wid]
                if state in ("dead", "hung", "timeout", "killed"):
                    try:
                        with open(w.err, "rb") as f:
                            e["stderr"] = f.read()[-spawn._STDERR_TAIL:] \
                                .decode("utf-8", "replace").strip()
                    except OSError:
                        e["stderr"] = ""
                out[wid] = e
            return out

        def fail(reason: str, failing: set) -> None:
            ranks = diag(reason, failing)
            transient = bool(failing) and all(
                resilience.classify_transient_text(
                    ranks[w].get("stderr", "")) for w in failing)
            spawn._merge_gang_trace(d)
            spawn._dump_flight_bundle("elastic_" + reason.replace(" ", "_"),
                                      ranks, d)
            raise ElasticError(reason, ranks, transient=transient,
                               recovery_failed=recovery_initiated)

        def evict(victims: List[int], reason: str) -> None:
            nonlocal epoch, shrinks, detect_ts, recovery_initiated
            survivors = [w for w in active() if w not in victims]
            # the resume point must be complete across the OLD mesh —
            # the victims' last committed shards included
            resume = store.complete_stage(epoch, active())
            if resume is None or len(survivors) < min_ranks or \
                    shrinks >= max_shrinks:
                fail("worker death" if reason == "dead" else "hung worker",
                     set(victims))
            if detect_ts is None:
                detect_ts = time.monotonic()
            recovery_initiated = True
            prev_workers = sorted(active(), key=lambda w: rank_of[w])
            prev_epoch = epoch
            epoch += 1
            shrinks += 1
            for i, w in enumerate(sorted(survivors,
                                         key=lambda w: rank_of[w])):
                rank_of[w] = i
            doc = {"epoch": epoch, "prev_epoch": prev_epoch,
                   "prev_workers": prev_workers,
                   "workers": {str(w): rank_of[w] for w in survivors},
                   "evicted": sorted(set(evicted_info) | set(victims)),
                   "resume_stage": resume, "reason": reason,
                   "coord": f"127.0.0.1:{spawn._free_port()}",
                   "ts": time.time()}
            _write_remesh(d, doc)
            for v in victims:
                evicted_info[v] = reason
                workers[v].evicted = True
                _teardown_victim(d, workers[v])
            _note_shrink(sorted(victims), len(prev_workers),
                         len(survivors))
            spawn._dump_flight_bundle(f"elastic_shrink_e{epoch}",
                                      diag(None, set()), d)

        try:
            for i in range(n_processes):
                launch(i, {}, n_processes, i)
            spawn._register_gang_health(
                d, [workers[w].proc for w in sorted(workers)],
                [workers[w].hb for w in sorted(workers)], start,
                evicted=lambda: {w for w in workers
                                 if workers[w].evicted or os.path.exists(
                                     os.path.join(d, f"evicted_{w}"))})
            while True:
                now = time.monotonic()
                if now >= deadline:
                    fail("gang timeout",
                         {w for w in active()
                          if workers[w].proc.poll() is None})
                order = active()
                reason, failing_idx = spawn._supervise(
                    [workers[w].proc for w in order],
                    [workers[w].hb for w in order],
                    now, min(1.0, deadline - now), hb_timeout)
                failing = {order[i] for i in failing_idx}
                if reason is None:
                    results = _collect(d, workers, order, rank_of)
                    spawn._merge_gang_trace(d)
                    wall = time.monotonic() - start
                    mttr = (time.monotonic() - detect_ts) \
                        if detect_ts is not None else None
                    if mttr is not None:
                        note_mttr(mttr)
                    report = {"epochs": epoch, "shrinks": shrinks,
                              "grows": grows,
                              "evicted": dict(evicted_info),
                              "final_nprocs": len(order),
                              "mttr_s": mttr, "wall_s": wall,
                              "ckpt": store.stats()}
                    return ElasticRun(results, report)
                if reason == "worker death":
                    evict(sorted(failing), "dead")
                elif reason == "hung worker":
                    evict(sorted(failing), "hung")
                else:  # slice expired: housekeeping
                    straggler = _find_straggler(d, store, epoch, active(),
                                                rank_of, frontier_seen,
                                                straggler_s)
                    if straggler is not None and \
                            len(active()) > min_ranks and \
                            shrinks < max_shrinks:
                        evict([straggler], "straggler")
                    elif grow and shrinks > grows and \
                            len(active()) < n_processes:
                        wid = _try_grow(d, store, workers, rank_of,
                                        evicted_info, epoch, stages,
                                        launch)
                        if wid is not None:
                            epoch += 1
                            grows += 1
                            _note_grow()
        finally:
            spawn._clear_gang_health()
            for w in workers.values():
                if w.proc.poll() is None:
                    w.proc.kill()
            for w in workers.values():
                try:
                    w.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
            for h in handles:
                h.close()


def _teardown_victim(d: str, w: _Worker) -> None:
    """Give an evicted-but-alive rank (straggler eviction) a grace
    window to exit clean before force-killing it; either way its
    diagnostic state is "evicted", not "dead"."""
    grace = time.monotonic() + float(config.elastic_evict_grace_s)
    while w.proc.poll() is None and time.monotonic() < grace:
        if os.path.exists(os.path.join(d, f"evicted_{w.wid}")):
            break
        time.sleep(_POLL_S)
    if w.proc.poll() is None:
        try:
            w.proc.send_signal(signal.SIGUSR1)
        except OSError:  # pragma: no cover
            pass
        dump_grace = time.monotonic() + 2.0
        while w.proc.poll() is None and time.monotonic() < dump_grace:
            time.sleep(_POLL_S)
        if w.proc.poll() is None:
            w.proc.kill()
    # the parent records the eviction even when the worker could not
    # (wedged rank): the marker is what /healthz and doctor read
    path = os.path.join(d, f"evicted_{w.wid}")
    if not os.path.exists(path):
        try:
            with open(path, "w") as f:
                json.dump({"worker": w.wid, "by": "parent",
                           "ts": time.time()}, f)
        except OSError:  # pragma: no cover
            pass


def _collect(d, workers, order, rank_of) -> List[object]:
    outs = []
    for wid in sorted(order, key=lambda w: rank_of[w]):
        path = workers[wid].out
        if not os.path.exists(path):
            raise ElasticError("missing result",
                               {wid: {"state": "dead", "returncode":
                                      workers[wid].proc.poll()}})
        with open(path, "rb") as f:
            outs.append(pickle.load(f))
    # sentinel test must be type-guarded: `!=` on a DataFrame shard is
    # elementwise, not a scalar
    return [o for o in outs
            if not (isinstance(o, str) and o == _EVICTED_SENTINEL)]


def _find_straggler(d, store, epoch, active, rank_of, frontier_seen,
                    straggler_s) -> Optional[int]:
    """Straggler-eviction policy: a rank the gang is *waiting for* —
    its checkpoint frontier is behind its peers' and has not advanced
    within `straggler_s` — is evicted like a dead one. Attribution
    prefers the comm observatory's lockstep arrival stamps when
    lockstep logs exist; the checkpoint frontier is the fallback
    signal. Disabled when `straggler_s` is 0."""
    if straggler_s <= 0 or len(active) < 2:
        return None
    sc = store.scan()
    tops = {w: max(sc.get((epoch, w), {-1})) for w in active}
    lo, hi = min(tops.values()), max(tops.values())
    if hi <= lo:  # nobody is behind
        frontier_seen.clear()
        return None
    laggards = [w for w in active if tops[w] == lo]
    now = time.monotonic()
    for w in active:
        prev = frontier_seen.get(w)
        if prev is None or prev[0] != tops[w]:
            frontier_seen[w] = (tops[w], now)
    stuck = [w for w in laggards
             if now - frontier_seen[w][1] >= straggler_s]
    if not stuck:
        return None
    try:
        from bodo_tpu.parallel import comm
        rk = comm.straggler_from_logs(d, len(active), epoch=epoch)
        if rk is not None:
            cand = [w for w in active if rank_of[w] == rk]
            if cand and cand[0] in stuck:
                return cand[0]
    except Exception:  # noqa: BLE001 - attribution is advisory
        pass
    return stuck[0]


def _try_grow(d, store, workers, rank_of, evicted_info, epoch, stages,
              launch) -> Optional[int]:
    """Grow path: once the shrunk mesh has a complete checkpoint of its
    own and stages remain, admit a replacement worker at the next
    stage boundary via one more epoch bump (reshard N-1 -> N)."""
    active = [w for w in sorted(workers) if not workers[w].evicted]
    resume = store.complete_stage(epoch, active)
    if resume is None or resume >= len(stages):
        return None
    wid = max(workers) + 1
    prev_workers = sorted(active, key=lambda w: rank_of[w])
    new_workers = prev_workers + [wid]
    for i, w in enumerate(new_workers):
        rank_of[w] = i
    doc = {"epoch": epoch + 1, "prev_epoch": epoch,
           "prev_workers": prev_workers,
           "workers": {str(w): rank_of[w] for w in new_workers},
           "evicted": sorted(evicted_info),
           "resume_stage": resume, "reason": "grow",
           "coord": f"127.0.0.1:{_free_port_late()}",
           "ts": time.time()}
    _write_remesh(d, doc)
    # the joiner forms its own single-process jax cluster on the FRESH
    # coordinator port from the remesh doc — never the original gang's,
    # which rank 0's still-running coordinator owns (the shared mesh
    # state rides host files); it adopts the posted epoch on entry
    launch(wid, {"BODO_TPU_ELASTIC_JOINER": "1",
                 "BODO_TPU_COORD": doc["coord"]}, 1, 0)
    return wid


def _free_port_late() -> int:
    from bodo_tpu import spawn
    return spawn._free_port()


# --------------------------------------------------------------------
# serving state (/healthz, scheduler, fleet)
# --------------------------------------------------------------------

_mu = threading.Lock()
_STATE = {"epoch": 0, "nprocs_full": None, "nprocs": None,
          "evicted": [], "capacity_frac": 1.0, "grow_pending": False,
          "shrinks": 0, "grows": 0, "resumes": 0, "last_mttr_s": None}
_QSTORE = CheckpointStore(None)
_qseq = 0


def _note_shrink(evicted: List[int], before: int, after: int) -> None:
    with _mu:
        _STATE["epoch"] += 1
        _STATE["shrinks"] += 1
        _STATE["evicted"] = sorted(set(_STATE["evicted"]) | set(evicted))
        if _STATE["nprocs_full"] is None:
            _STATE["nprocs_full"] = before
        _STATE["nprocs"] = after
        _STATE["capacity_frac"] = round(
            after / max(1, _STATE["nprocs_full"]), 4)
        _STATE["grow_pending"] = True


def _note_grow() -> None:
    with _mu:
        _STATE["epoch"] += 1
        _STATE["grows"] += 1
        full = _STATE["nprocs_full"] or 1
        _STATE["nprocs"] = min(full, (_STATE["nprocs"] or full) + 1)
        _STATE["capacity_frac"] = round(_STATE["nprocs"] / full, 4)
        if _STATE["nprocs"] >= full:
            _STATE["evicted"] = []
            _STATE["grow_pending"] = False


def note_resume() -> None:
    with _mu:
        _STATE["resumes"] += 1


def note_mttr(seconds: float) -> None:
    with _mu:
        _STATE["last_mttr_s"] = round(float(seconds), 4)


def note_query_boundary() -> bool:
    """Scheduler hook, called between queries: the background grow path
    re-admits replacement capacity at the next query boundary (the
    next gang launch runs at full width again). Returns True when
    capacity was restored."""
    if not config.elastic or not config.elastic_grow:
        return False
    with _mu:
        if not _STATE["grow_pending"]:
            return False
        _STATE["grows"] += 1
        _STATE["nprocs"] = _STATE["nprocs_full"]
        _STATE["capacity_frac"] = 1.0
        _STATE["evicted"] = []
        _STATE["grow_pending"] = False
    return True


def observe_stage(node, seconds: float = 0.0) -> None:
    """Plan-executor hook at every AQE stage boundary (physical._exec,
    right after adaptive.observe_stage): register the materialized
    stage output as an in-process checkpoint anchor. The semantic
    result cache owns the bytes (its host-spill tier is the durable
    copy a resumed suffix reads back); the store tracks the two-phase
    registration and byte accounting for /healthz."""
    global _qseq
    if not config.elastic:
        return
    try:
        nbytes = 0
        t = getattr(node, "_cached", None)
        if t is not None:
            from bodo_tpu.runtime.memory_governor import table_device_bytes
            nbytes = table_device_bytes(t)
        with _mu:
            _qseq += 1
            seq = _qseq
        tok = _QSTORE.register(stage=seq, epoch=_STATE["epoch"], worker=0,
                               meta={"bytes": nbytes,
                                     "wall_s": float(seconds)})
        _QSTORE.commit(tok)
    except Exception:  # noqa: BLE001 - accounting never fails a query
        pass


def head() -> dict:
    """Elastic block for /healthz: mesh epoch, evicted workers, the
    reduced capacity the fleet admission twin rescales by, and the
    checkpoint-store counters."""
    with _mu:
        out = dict(_STATE)
        out["evicted"] = list(out["evicted"])
    out["checkpoints"] = _QSTORE.stats()
    return out


def reset() -> None:
    global _QSTORE, _qseq
    with _mu:
        _STATE.update({"epoch": 0, "nprocs_full": None, "nprocs": None,
                       "evicted": [], "capacity_frac": 1.0,
                       "grow_pending": False, "shrinks": 0, "grows": 0,
                       "resumes": 0, "last_mttr_s": None})
        _qseq = 0
        _QSTORE = CheckpointStore(None)
