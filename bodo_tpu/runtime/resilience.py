"""Resilience layer: fault injection, retry envelope, error taxonomy.

The engine's recovery machinery (OOM-retry at stage boundaries,
partitioned spill, pandas fallbacks) existed but was only exercisable by
real failures. This module makes faults first-class, the analogue of the
reference engine treating worker supervision as part of the runtime
rather than something MPI does for you (reference: bodo/spawn/spawner.py
spawner/worker model, bodo/libs/memory_budget.py threshold enforcement).

Three parts:

1. FAULT-INJECTION REGISTRY — named points that production code calls
   via `maybe_inject(point)`:

       collective           distributed-op dispatch (shuffle/psum paths)
       device_put           host->device scatter (shard_host_array)
       io.read              parquet/csv/json readers (per attempt)
       io.write             parquet writers (per attempt)
       spawn.worker_start   spawned worker, BEFORE the jax import
       stage.boundary       plan-executor stage entry (both executors)
       fleet.serve          fleet controller, per routed submission
       elastic.checkpoint   elastic worker, at every stage-boundary
                            checkpoint registration (kill here is the
                            canonical mid-pipeline rank loss)
       elastic.remesh       elastic worker, on adopting a new mesh
                            epoch (before renumbering) — recovery of
                            recovery; a fault here must fall back to
                            the gang-level retry, never wedge
       elastic.resume       elastic worker, after renumbering/lockstep
                            re-namespacing, before the recovery
                            reshard of the last checkpoint

   Tests and chaos runs arm them with a spec string, either in-process
   (`set_config(faults=...)`) or via `BODO_TPU_FAULTS=<spec>` in the
   environment so spawned workers inherit them:

       spec   := entry ("," entry)*
       entry  := point ["@" rank] "=" action
       action := "raise:" NAME [":" nth [":" times]]
               | "latency:" SECONDS [":" nth [":" times]]
               | "kill" [":" nth]

   `NAME` resolves against builtins (OSError, TimeoutError, ...); any
   other name raises `FaultInjected` with the name in the message (so
   `raise:RESOURCE_EXHAUSTED` exercises the governor's OOM envelope).
   `nth` is the 1-based call at which the fault starts firing (default
   1); `times` is how many consecutive calls fire (default 1; 0 =
   every call from `nth` on). `@rank` restricts the entry to one
   spawned rank (matched against BODO_TPU_PROC_ID).

2. RETRY ENVELOPE — `retry_call(fn, ...)`: exponential backoff +
   jitter + deadline over a transient-error taxonomy:

       resource_exhausted   XLA RESOURCE_EXHAUSTED / out-of-memory
                            (unified with the memory governor's
                            `is_oom`, which delegates here)
       coordination         jax.distributed / coordination-service
                            flake (DEADLINE_EXCEEDED, UNAVAILABLE,
                            failed-to-connect, barrier timeout)
       filesystem           OSError flake that is NOT a deterministic
                            error (missing file, permissions)

3. COUNTERS — every injected fault, retry, degraded stage, and gang
   retry lands in `stats()`, which the tracing profile, chrome-trace
   dump, and bench JSON all embed, so a degraded artifact says WHY it
   degraded.

IMPORTANT: this module must stay importable WITHOUT the bodo_tpu
package (stdlib imports only at module scope). Spawned workers load it
straight from its file path before importing jax, so a `kill` armed at
`spawn.worker_start` costs ~0.2s, not a full jax import. When the
package IS imported, knobs come from `bodo_tpu.config`; standalone they
come from environment variables.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

# ---------------------------------------------------------------------------
# config access (lazy: works standalone AND inside the package)
# ---------------------------------------------------------------------------


def _cfg(name: str, env: str, default, cast):
    """Read a knob from bodo_tpu.config when the package is already
    imported (never triggers the package import — that would pull jax
    into a pre-import worker), else from the environment."""
    m = sys.modules.get("bodo_tpu.config")
    c = getattr(m, "config", None) if m is not None else None
    if c is not None and hasattr(c, name):
        return getattr(c, name)
    v = os.environ.get(env)
    if v in (None, ""):
        return default
    return cast(v)


# ---------------------------------------------------------------------------
# fault-injection registry
# ---------------------------------------------------------------------------

POINTS = ("collective", "device_put", "io.read", "io.write",
          "spawn.worker_start", "stage.boundary", "fleet.serve",
          "elastic.checkpoint", "elastic.remesh", "elastic.resume")


class FaultInjected(RuntimeError):
    """Raised by an armed injection point whose exception name does not
    resolve to a builtin exception class. The chosen name is embedded in
    the message so substring-matching recovery layers (e.g. the memory
    governor's RESOURCE_EXHAUSTED check) treat it like the real thing."""

    def __init__(self, point: str, name: str, call_no: int):
        self.point = point
        self.fault_name = name
        super().__init__(
            f"injected fault at {point} (call {call_no}): {name}")


class _Fault:
    __slots__ = ("point", "rank", "kind", "arg", "nth", "times")

    def __init__(self, point, rank, kind, arg, nth, times):
        self.point = point
        self.rank = rank      # None = every rank
        self.kind = kind      # "raise" | "latency" | "kill"
        self.arg = arg        # exception name | latency seconds
        self.nth = nth        # 1-based first firing call
        self.times = times    # firings from nth on; 0 = unlimited

    def spec(self) -> str:
        at = f"@{self.rank}" if self.rank is not None else ""
        if self.kind == "kill":
            return f"{self.point}{at}=kill:{self.nth}"
        return (f"{self.point}{at}={self.kind}:{self.arg}"
                f":{self.nth}:{self.times}")


_lock = threading.Lock()
_armed: Optional[List[_Fault]] = None   # None = not yet armed from env
_calls: Dict[str, int] = {}

_STATS_ZERO = lambda: {  # noqa: E731 - tiny factory
    "faults_fired": {}, "retries": {}, "retries_by_category": {},
    "degraded_stages": {}, "gang_retries": 0,
}
_stats = _STATS_ZERO()


def parse_faults(spec: str) -> List[_Fault]:
    """Parse a fault spec string (see module docstring for the grammar).
    Raises ValueError on malformed entries or unknown points."""
    out: List[_Fault] = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        if "=" not in entry:
            raise ValueError(f"fault entry {entry!r}: expected point=action")
        target, action = entry.split("=", 1)
        rank: Optional[int] = None
        if "@" in target:
            target, r = target.split("@", 1)
            rank = int(r)
        if target not in POINTS:
            raise ValueError(
                f"unknown fault point {target!r} (valid: {POINTS})")
        parts = action.split(":")
        kind = parts[0]
        if kind == "kill":
            nth = int(parts[1]) if len(parts) > 1 else 1
            out.append(_Fault(target, rank, "kill", None, nth, 1))
        elif kind in ("raise", "latency"):
            if len(parts) < 2:
                raise ValueError(
                    f"fault entry {entry!r}: {kind} needs an argument")
            arg = parts[1] if kind == "raise" else float(parts[1])
            nth = int(parts[2]) if len(parts) > 2 else 1
            times = int(parts[3]) if len(parts) > 3 else 1
            out.append(_Fault(target, rank, kind, arg, nth, times))
        else:
            raise ValueError(
                f"fault entry {entry!r}: unknown action {kind!r} "
                f"(raise/latency/kill)")
        if out[-1].nth < 1:
            raise ValueError(f"fault entry {entry!r}: nth must be >= 1")
    return out


def arm(spec: str) -> None:
    """Arm the registry from a spec string (empty disarms). Per-point
    call counters reset so `nth` is deterministic from this moment."""
    global _armed
    faults = parse_faults(spec or "")
    with _lock:
        _armed = faults
        _calls.clear()


def disarm() -> None:
    arm("")


def armed() -> List[str]:
    """Spec strings of the currently armed faults (diagnostics)."""
    with _lock:
        return [f.spec() for f in (_armed or [])]


def current_rank() -> Optional[int]:
    """Rank for @rank fault filters: the spawned worker's
    BODO_TPU_PROC_ID, else the jax process index when jax is already
    imported (never imports jax itself)."""
    v = os.environ.get("BODO_TPU_PROC_ID")
    if v not in (None, ""):
        return int(v)
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            return None
    return None


def _ensure_armed() -> List[_Fault]:
    global _armed
    if _armed is None:
        spec = _cfg("faults", "BODO_TPU_FAULTS", "", str)
        try:
            faults = parse_faults(spec)
        except ValueError:
            faults = []
            sys.stderr.write(
                f"bodo_tpu.resilience: ignoring malformed "
                f"BODO_TPU_FAULTS={spec!r}\n")
        # publish under the lock: a concurrent arm()/disarm() must
        # never lose its spec to this lazy env-arming racing it
        with _lock:
            if _armed is None:
                _armed = faults
    return _armed


def maybe_inject(point: str) -> None:
    """Fire any armed faults for `point`. Near-free when nothing is
    armed (one attribute read + truthiness check)."""
    faults = _armed
    if faults is None:
        faults = _ensure_armed()
    if not faults:
        return
    with _lock:
        n = _calls.get(point, 0) + 1
        _calls[point] = n
        live = [f for f in faults if f.point == point]
    if not live:
        return
    rank = current_rank()
    for f in live:
        if f.rank is not None and f.rank != rank:
            continue
        if n < f.nth or (f.times and n >= f.nth + f.times):
            continue
        with _lock:
            _stats["faults_fired"][point] = \
                _stats["faults_fired"].get(point, 0) + 1
        if f.kind == "latency":
            time.sleep(float(f.arg))
            continue
        if f.kind == "kill":
            sys.stderr.write(
                f"bodo_tpu.resilience: injected kill at {point} "
                f"(call {n}, rank {rank})\n")
            sys.stderr.flush()
            # the dying rank is the one whose timeline the post-mortem
            # needs most: leave its trace shard in the gang side channel
            # before os._exit skips every atexit/finally path
            _dump_trace_shard_best_effort()
            os._exit(137)
        # kind == "raise"
        import builtins
        cls = getattr(builtins, str(f.arg), None)
        if isinstance(cls, type) and issubclass(cls, BaseException):
            raise cls(f"injected fault at {point} (call {n})")
        raise FaultInjected(point, str(f.arg), n)


def _dump_trace_shard_best_effort() -> None:
    """Write this process's trace shard into the gang's shared dir (the
    spawner merges shards into the flight-recorder bundle). Uses
    sys.modules.get so the stdlib-only import rule holds: a pre-import
    worker (no tracing module loaded) simply has nothing to dump."""
    tr = sys.modules.get("bodo_tpu.utils.tracing")
    d = os.environ.get("BODO_TPU_TRACE_SHARD_DIR")
    if tr is None or not d:
        return
    try:
        if tr.has_events():
            tr.dump_shard(d)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# transient-error taxonomy
# ---------------------------------------------------------------------------

# shardcheck analysis errors (by class name — this module must stay
# stdlib-only and cannot import bodo_tpu.analysis): correctness bugs
# whose messages mention collectives, so substring taxonomies below
# would otherwise retry or degrade them away instead of surfacing them
_ANALYSIS_ERRORS = ("LockstepError", "PlanInvariantError")


def _is_analysis_error(exc: BaseException) -> bool:
    return type(exc).__name__ in _ANALYSIS_ERRORS


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")
_COORD_MARKERS = (
    "DEADLINE_EXCEEDED", "UNAVAILABLE", "failed to connect",
    "Connection reset", "connection attempts failed", "Socket closed",
    "Barrier timed out", "coordination service", "Address already in use",
    "heartbeat", "ConnectionResetError", "ConnectionRefusedError",
)
# OSError subclasses that are deterministic, not flake — never retried
_FS_PERMANENT = (FileNotFoundError, PermissionError, IsADirectoryError,
                 NotADirectoryError, FileExistsError)


def is_resource_exhausted(exc: BaseException) -> bool:
    """XLA RESOURCE_EXHAUSTED / allocator OOM (the memory governor's
    `is_oom` delegates here — one taxonomy for the whole engine)."""
    msg = f"{type(exc).__name__}: {exc}"
    return any(m in msg for m in _OOM_MARKERS)


def classify_transient(exc: BaseException) -> Optional[str]:
    """Category name when `exc` looks transient (worth retrying), else
    None. Injected `FaultInjected` faults are NOT transient — to test
    the retry path, inject a real transient class (e.g. OSError).
    Shardcheck analysis errors (LockstepError/PlanInvariantError) are
    never transient: they report divergence bugs, not flake."""
    if isinstance(exc, FaultInjected) or _is_analysis_error(exc):
        return None
    if is_resource_exhausted(exc):
        return "resource_exhausted"
    msg = f"{type(exc).__name__}: {exc}"
    if any(m in msg for m in _COORD_MARKERS):
        return "coordination"
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return "coordination"
    if isinstance(exc, OSError) and not isinstance(exc, _FS_PERMANENT):
        return "filesystem"
    return None


def classify_transient_text(text: str) -> Optional[str]:
    """Taxonomy over captured stderr (the spawner classifies dead
    workers from their output, not a live exception object)."""
    if not text:
        return None
    if any(m in text for m in _OOM_MARKERS):
        return "resource_exhausted"
    if any(m in text for m in _COORD_MARKERS):
        return "coordination"
    if "terminate called without an active exception" in text \
            and "Traceback" not in text:
        # a bare C++ std::terminate with NO Python traceback: the worker
        # died inside native thread machinery (TSL/XLA startup or
        # teardown under load), never reaching user code — retry the
        # gang like a coordination flake; a deterministic native bug
        # still fails the bounded retry
        return "native_abort"
    return None


def is_degradable(exc: BaseException) -> bool:
    """True when a stage failure should trigger replicated re-execution:
    an injected `collective` fault, or a non-OOM internal/collective
    runtime error from a sharded computation. Shardcheck analysis
    errors are excluded by class name BEFORE the marker matching: a
    LockstepError's message names the diverging collective, and
    degrading it to a replicated re-run would mask the divergence bug
    it exists to surface."""
    if _is_analysis_error(exc):
        return False
    if isinstance(exc, FaultInjected):
        return exc.point == "collective"
    if is_resource_exhausted(exc):
        return False  # the OOM envelope owns this
    msg = f"{type(exc).__name__}: {exc}"
    return any(m in msg for m in (
        "INTERNAL:", "all-reduce", "all-to-all", "all_gather",
        "AllReduce", "AllToAll", "CollectivePermute", "collective",
        "ppermute"))


# ---------------------------------------------------------------------------
# retry envelope
# ---------------------------------------------------------------------------


class RetryPolicy:
    """Exponential backoff + jitter + deadline. Defaults come from
    BODO_TPU_RETRY_ATTEMPTS / _RETRY_BASE_S / _RETRY_DEADLINE_S (or the
    same-named config fields when the package is imported)."""

    def __init__(self, max_attempts: Optional[int] = None,
                 base_s: Optional[float] = None,
                 factor: float = 2.0,
                 max_backoff_s: float = 10.0,
                 deadline_s: Optional[float] = None):
        self.max_attempts = int(max_attempts if max_attempts is not None
                                else _cfg("retry_attempts",
                                          "BODO_TPU_RETRY_ATTEMPTS", 3,
                                          int))
        self.base_s = float(base_s if base_s is not None
                            else _cfg("retry_base_s",
                                      "BODO_TPU_RETRY_BASE_S", 0.05,
                                      float))
        self.factor = float(factor)
        self.max_backoff_s = float(max_backoff_s)
        self.deadline_s = float(deadline_s if deadline_s is not None
                                else _cfg("retry_deadline_s",
                                          "BODO_TPU_RETRY_DEADLINE_S",
                                          30.0, float))

    def backoff(self, attempt: int) -> float:
        """Backoff before attempt `attempt`+1 (attempt is 1-based), with
        +/-50% jitter so gang-wide retries don't synchronize."""
        raw = min(self.base_s * (self.factor ** (attempt - 1)),
                  self.max_backoff_s)
        return raw * (0.5 + random.random())


def retry_call(fn: Callable[[], object], *, label: str,
               point: Optional[str] = None,
               policy: Optional[RetryPolicy] = None,
               classify: Callable[[BaseException], Optional[str]]
               = classify_transient,
               on_retry: Optional[Callable[[BaseException, int], None]]
               = None):
    """Call `fn()` under the retry envelope.

    `point` (optional) names a fault-injection point fired before EVERY
    attempt — an armed one-shot flake is absorbed by the retry, which is
    exactly the behavior chaos tests assert. Non-transient errors (per
    `classify`) raise immediately; transient ones retry with backoff
    until attempts or the deadline run out. Every retry is counted in
    `stats()["retries"][label]`.
    """
    p = policy or RetryPolicy()
    t0 = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            if point:
                maybe_inject(point)
            return fn()
        except Exception as e:
            cat = classify(e)
            elapsed = time.monotonic() - t0
            if (cat is None or attempt >= p.max_attempts
                    or elapsed >= p.deadline_s):
                raise
            delay = min(p.backoff(attempt),
                        max(p.deadline_s - elapsed, 0.0))
            with _lock:
                _stats["retries"][label] = \
                    _stats["retries"].get(label, 0) + 1
                _stats["retries_by_category"][cat] = \
                    _stats["retries_by_category"].get(cat, 0) + 1
            sys.stderr.write(
                f"bodo_tpu.resilience: {label} attempt {attempt} failed "
                f"({cat}: {type(e).__name__}: {str(e)[:160]}); retrying "
                f"in {delay:.2f}s\n")
            if on_retry is not None:
                on_retry(e, attempt)
            time.sleep(delay)


# ---------------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------------


def count_degradation(stage: str) -> None:
    with _lock:
        _stats["degraded_stages"][stage] = \
            _stats["degraded_stages"].get(stage, 0) + 1


def count_gang_retry() -> None:
    with _lock:
        _stats["gang_retries"] += 1


def stats() -> dict:
    """JSON-safe snapshot of all resilience counters plus the armed
    fault specs (embedded in tracing dumps and bench artifacts)."""
    with _lock:
        return {
            "faults_armed": [f.spec() for f in (_armed or [])],
            "point_calls": dict(_calls),
            "faults_fired": dict(_stats["faults_fired"]),
            "retries": dict(_stats["retries"]),
            "retries_by_category": dict(_stats["retries_by_category"]),
            "degraded_stages": dict(_stats["degraded_stages"]),
            "gang_retries": _stats["gang_retries"],
        }


def reset_stats() -> None:
    """Zero the counters (tests); armed faults are untouched."""
    global _stats
    with _lock:
        _stats = _STATS_ZERO()
        _calls.clear()


# ---------------------------------------------------------------------------
# heartbeat (spawn worker side)
# ---------------------------------------------------------------------------


_hb_stop: Optional[threading.Event] = None
_hb_last: Optional[float] = None


def last_heartbeat_age() -> Optional[float]:
    """Seconds since this process's own heartbeat thread last beat, or
    None when no heartbeat ever ran (telemetry sampler input: a large
    age in a live process means the beat thread is starved/stopped)."""
    with _lock:
        t = _hb_last
    if t is None:
        return None
    return max(0.0, time.time() - t)


def start_heartbeat(path: str, interval_s: Optional[float] = None
                    ) -> threading.Event:
    """Touch `path` every `interval_s` from a daemon thread. The spawner
    watches the file's mtime: a wedged worker (no beat for the
    supervision window) gets its whole gang torn down with diagnostics
    instead of stalling everyone until the gang timeout. Returns the
    stop event."""
    global _hb_stop
    if interval_s is None:
        interval_s = _cfg("spawn_hb_interval_s",
                          "BODO_TPU_SPAWN_HB_INTERVAL", 0.5, float)
    stop = threading.Event()
    with _lock:
        _hb_stop = stop

    def _beat():
        global _hb_last
        while not stop.is_set():
            try:
                with open(path, "w") as f:
                    f.write(str(time.time()))
                with _lock:
                    _hb_last = time.time()
            except OSError:
                pass
            stop.wait(interval_s)

    t = threading.Thread(target=_beat, name="bodo-tpu-heartbeat",
                         daemon=True)
    t.start()
    return stop


def stop_heartbeat() -> None:
    """Silence this process's heartbeat thread. Chaos-test hook: a
    worker that stops beating AFTER its first beat landed simulates a
    process wedged mid-computation (the hb file exists but its mtime
    goes stale), exercising the supervisor's mtime-age path rather than
    the no-file startup-grace fallback."""
    if _hb_stop is not None:
        _hb_stop.set()
