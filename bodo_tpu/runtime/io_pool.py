"""Pipelined I/O runtime: shared bounded thread pool + prefetch iterator.

Analogue of the reference engine's parallel scan units + streaming
ArrowReader (bodo/io/parquet_reader.cpp distributes scan units over a
reader thread pool; bodo/io/arrow_reader.h streams batches while the
pipeline consumes) and of Pathways-style asynchronous dataflow: host
decode work runs on pool threads so the device never waits for Arrow.

Three pieces:

  * ``io_pool()`` — one process-wide bounded ``ThreadPoolExecutor``
    (``config.io_threads`` workers) shared by every parallel decode site
    (parquet row groups, CSV byte-range chunks).
  * ``pool_map_ordered(fn, items)`` — map on the pool with a bounded
    in-flight window and ORDERED reassembly, so parallel reads are
    byte-identical to the serial reader.
  * ``Prefetcher`` — wraps a batch iterator; a worker thread decodes
    batch k+1 while the consumer (device compute) runs batch k. The
    queue depth is admission-charged against the memory governor
    (depth x batch bytes, non-blocking: under pressure the effective
    depth derates instead of stalling). Exceptions — including armed
    ``io.read`` faults fired on the worker — are captured and re-raised
    at the consumer; ``close()`` shuts the worker down promptly even
    mid-decode (no leaked threads).

All ``io:*`` observability counters (decode/stall seconds, prefetch
hits, footer-cache hits, parallel decode units) live here so
``tracing.profile()``/``dump()`` and the bench JSON read one registry.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Callable, Iterable, Iterator, Optional

from bodo_tpu.config import config

# ---------------------------------------------------------------------------
# io:* counter registry
# ---------------------------------------------------------------------------

_stats_lock = threading.Lock()


def _zero() -> dict:
    return {
        "decode_s": 0.0,        # worker-side time spent decoding batches
        "decode_batches": 0,
        "decode_bytes": 0,
        "stall_s": 0.0,         # consumer-side time blocked on the queue
        "stalls": 0,
        "prefetch_hits": 0,     # batches served with zero consumer wait
        "prefetch_streams": 0,
        "prefetch_depth": 0,    # max effective depth seen
        "footer_hits": 0,       # parquet footer cache
        "footer_misses": 0,
        "parallel_units": 0,    # row groups / csv chunks decoded on pool
        "parallel_reads": 0,
        # device-side parquet decode (io/device_decode.py)
        "device_decode_s": 0.0,       # consumer-side on-chip decode time
        "device_decode_pages": 0,     # pages decoded by jitted programs
        "device_decode_cols": 0,      # column chunks decoded on device
        "device_fallback_cols": 0,    # column chunks demoted to host
        "device_decode_errors": 0,    # planned-but-failed device decodes
        "device_decode_bytes": 0,     # decoded bytes produced on device
        "host_decode_bytes": 0,       # decoded bytes produced by pyarrow
        "raw_bytes": 0,               # raw (compressed) page bytes shipped
        # Pallas kernel engagement inside page programs (trace-time
        # counters: bumped when the kernel routes into a compiled spec)
        "pallas_expand_traced": 0,    # hybrid RLE/bit-packed expand
        "pallas_dict_gather": 0,      # dictionary-decode gather
    }


_io = _zero()


def count(key: str, n: int = 1) -> None:
    with _stats_lock:
        _io[key] += n


def add_time(key: str, seconds: float) -> None:
    with _stats_lock:
        _io[key] += seconds


def record_depth(depth: int) -> None:
    with _stats_lock:
        _io["prefetch_depth"] = max(_io["prefetch_depth"], int(depth))


def io_stats() -> dict:
    """Snapshot of the io:* counters plus the derived overlap figures:
    ``overlap_s`` is decode time hidden behind consumer compute
    (decode_s - stall_s, floored at 0), ``overlap_ratio`` its fraction
    of total decode time."""
    with _stats_lock:
        out = dict(_io)
    overlap = max(out["decode_s"] - out["stall_s"], 0.0)
    out["overlap_s"] = overlap
    out["overlap_ratio"] = (overlap / out["decode_s"]
                            if out["decode_s"] > 0 else 0.0)
    # fraction of decoded output bytes produced on device rather than by
    # host pyarrow (the scan target from ROADMAP item 3)
    dd, hd = out["device_decode_bytes"], out["host_decode_bytes"]
    out["device_decode_frac"] = dd / (dd + hd) if (dd + hd) > 0 else 0.0
    return out


def reset_io_stats() -> None:
    global _io
    with _stats_lock:
        _io = _zero()


# ---------------------------------------------------------------------------
# shared bounded pool
# ---------------------------------------------------------------------------

_pool = None
_pool_threads = 0
_pool_lock = threading.Lock()


def io_thread_count() -> int:
    """Resolved worker count: ``config.io_threads``; <= 0 means auto
    (min(8, cpu_count), at least 2 so decode can overlap I/O even on a
    single-core host — Arrow releases the GIL while parsing)."""
    n = int(config.io_threads)
    if n <= 0:
        import os
        n = min(8, max(2, os.cpu_count() or 1))
    return n


def io_pool():
    """The process-wide I/O executor (rebuilt when io_threads changes)."""
    global _pool, _pool_threads
    n = io_thread_count()
    with _pool_lock:
        if _pool is None or _pool_threads != n:
            if _pool is not None:
                _pool.shutdown(wait=False)
            from concurrent.futures import ThreadPoolExecutor
            _pool = ThreadPoolExecutor(max_workers=n,
                                       thread_name_prefix="bodo-tpu-io")
            _pool_threads = n
        return _pool


def reset_pool() -> None:
    """Shut down the shared pool (tests / set_config(io_threads=...))."""
    global _pool
    with _pool_lock:
        if _pool is not None:
            _pool.shutdown(wait=False)
            _pool = None


def pool_map_ordered(fn: Callable, items: Iterable,
                     window: Optional[int] = None) -> Iterator:
    """Map `fn` over `items` on the shared pool, yielding results IN
    ORDER with at most `window` tasks in flight (default: pool width +
    1). A task exception propagates at its ordered position; remaining
    in-flight tasks are cancelled/abandoned."""
    ex = io_pool()
    w = max(int(window or (io_thread_count() + 1)), 1)
    pending: deque = deque()
    src = iter(items)
    try:
        for item in src:
            pending.append(ex.submit(fn, item))
            count("parallel_units")
            if len(pending) >= w:
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()
    finally:
        for f in pending:
            f.cancel()


# ---------------------------------------------------------------------------
# prefetching iterator
# ---------------------------------------------------------------------------

def _default_nbytes(item) -> int:
    """Best-effort size of a prefetched item for governor accounting."""
    try:
        from bodo_tpu.runtime.memory_governor import table_device_bytes
        if hasattr(item, "columns") and hasattr(item, "nrows"):
            return table_device_bytes(item)
    except Exception:
        pass
    nb = getattr(item, "nbytes", None)
    try:
        return int(nb) if nb is not None else 0
    except Exception:
        return 0


_ITEM, _DONE, _ERR = "item", "done", "err"


class Prefetcher:
    """Bounded-queue lookahead over a batch iterator.

    Lazy: the worker thread starts on the first ``__next__`` (so a
    stream that is built but never consumed costs nothing and leaks
    nothing). The first decoded batch sizes a governor admission of
    depth x batch-bytes; under memory pressure the grant derates the
    EFFECTIVE depth rather than blocking the stream. Worker-side
    exceptions (armed ``io.read`` faults included) re-raise at the
    consumer in stream position."""

    def __init__(self, src: Iterator, depth: Optional[int] = None,
                 label: str = "stream",
                 nbytes_of: Optional[Callable] = None):
        self._src = src
        self._depth = max(int(depth if depth is not None
                              else config.prefetch_depth), 1)
        self._label = label
        self._nbytes_of = nbytes_of or _default_nbytes
        self._q: queue.Queue = queue.Queue()
        self._cv = threading.Condition()
        self._produced = 0
        self._consumed = 0
        self._eff = self._depth
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._grant = None
        self._closed = False

    # -- worker side ---------------------------------------------------------

    def _run(self) -> None:
        src = self._src
        first = True
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(src)
                except StopIteration:
                    self._q.put((_DONE, None))
                    return
                except BaseException as e:  # noqa: BLE001 - re-raised at consumer
                    self._q.put((_ERR, e))
                    return
                dt = time.perf_counter() - t0
                nb = 0
                try:
                    nb = int(self._nbytes_of(item))
                except Exception:
                    nb = 0
                with _stats_lock:
                    _io["decode_s"] += dt
                    _io["decode_batches"] += 1
                    _io["decode_bytes"] += nb
                if first:
                    first = False
                    self._admit(nb)
                with self._cv:
                    while (self._produced - self._consumed) >= self._eff \
                            and not self._stop.is_set():
                        self._cv.wait(0.05)
                    if self._stop.is_set():
                        return
                    self._q.put((_ITEM, item))
                    self._produced += 1
        finally:
            self._release_grant()

    def _admit(self, nbytes: int) -> None:
        """Charge depth x batch-bytes against the governor's derived
        budget. Non-blocking: a reduced grant derates the effective
        lookahead depth instead of stalling the stream."""
        if nbytes <= 0:
            record_depth(self._eff)
            return
        try:
            from bodo_tpu.runtime.memory_governor import governor
            g = governor().admit(f"io_prefetch:{self._label}",
                                 want=self._depth * nbytes, wait=False)
        except Exception:
            record_depth(self._eff)
            return
        self._grant = g
        if g.budget:
            self._eff = max(1, min(self._depth,
                                   int(g.budget) // max(nbytes, 1)))
        g.update(self._eff * nbytes)
        record_depth(self._eff)

    def _release_grant(self) -> None:
        g = self._grant
        if g is not None:
            try:
                g.release()
            except Exception:
                pass

    # -- consumer side -------------------------------------------------------

    def _ensure_started(self) -> None:
        if self._thread is None and not self._closed:
            count("prefetch_streams")
            t = threading.Thread(target=self._run,
                                 name="bodo-tpu-prefetch", daemon=True)
            self._thread = t
            t.start()

    def __iter__(self) -> "Prefetcher":
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        self._ensure_started()
        try:
            kind, payload = self._q.get_nowait()
            count("prefetch_hits")
        except queue.Empty:
            t0 = time.perf_counter()
            while True:
                try:
                    kind, payload = self._q.get(timeout=0.05)
                    break
                except queue.Empty:
                    if self._stop.is_set():
                        raise StopIteration from None
                    t = self._thread
                    if t is not None and not t.is_alive():
                        # worker died without a sentinel (defensive)
                        raise StopIteration from None
            with _stats_lock:
                _io["stall_s"] += time.perf_counter() - t0
                _io["stalls"] += 1
        with self._cv:
            self._consumed += 1
            self._cv.notify_all()
        if kind is _DONE:
            self._closed = True
            raise StopIteration
        if kind is _ERR:
            self._closed = True
            raise payload
        return payload

    def close(self) -> None:
        """Stop the worker, release the governor charge, and close the
        wrapped source. Safe to call repeatedly and from any thread."""
        self._closed = True
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._release_grant()
        if t is None or not t.is_alive():
            close = getattr(self._src, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    def __del__(self):  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def prefetched(src: Iterator, label: str = "stream",
               depth: Optional[int] = None) -> Iterator:
    """Wrap a batch source with prefetching when enabled
    (``config.prefetch_depth`` > 0; 0 disables and returns `src`
    unchanged). Returned as a generator so abandonment (GC of a
    half-consumed stream) still closes the worker via ``finally``."""
    d = int(depth if depth is not None else config.prefetch_depth)
    if d <= 0:
        return src

    def gen():
        pf = Prefetcher(src, depth=d, label=label)
        try:
            yield from pf
        finally:
            pf.close()
    return gen()
