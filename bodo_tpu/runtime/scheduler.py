"""Multi-tenant query scheduler: one resident gang, many sessions.

The Pathways design point (PAPERS §2): a centralized controller
multiplexes many logical plans onto ONE warm SPMD gang instead of every
client paying gang spawn + jax.distributed init. Clients hold a
:class:`Session` (thin handles minted by ``bodo_tpu.serve``) and submit
plan thunks; a small worker pool drains the per-session queues through
``plan/physical.execute`` with the session pinned in a contextvar so
every layer underneath (result cache, sql plan cache, EXPLAIN, governor
grants) attributes its work to the right tenant.

Three mechanisms, in dispatch order:

1. ADMISSION — every submit is screened against the observability the
   engine already exports, via :class:`AdmissionSignals`:
     * governor occupancy >= ``serve_shed_occupancy`` (or an OOM retry /
       result-cache pressure shed since the last decision) → shed the
       request with a typed :class:`Overloaded`;
     * ``unhealthy_ranks`` on /healthz → :class:`Degraded` rejection
       unless the session opted into degraded service;
     * an ``xla_recompile_storm`` whose signature this session's own
       queries compiled under → :class:`BackOff` (shape-bucket churn
       must not evict other tenants' executables);
     * a comm-skewed gang (``comm.wait_frac`` head) → :class:`BackOff`
       for sessions whose own recent queries are comm-wait dominated.
   ``signals_from_health`` / ``signals_from_metrics`` parse remote
   /healthz JSON and /metrics Prometheus text into the same structure
   ``local_signals()`` builds in-process, so a fleet controller makes
   the identical decision from a scrape.

2. FAIR SHARE — per-session FIFO queues drained by weighted virtual
   time: each session accrues ``wall / weight`` as it is served and the
   lowest accrued time runs next, with priority aging (head-of-queue
   wait discounts virtual time at 1/``serve_aging_s`` per second) so a
   starved low-weight session eventually wins the gang.

3. BACKPRESSURE — queues are bounded (``serve_queue_depth`` per
   session, ``serve_max_pending`` total); overflow raises
   :class:`Overloaded` with a measured ``retry_after_s`` hint (queue
   length x the session's EWMA query wall) instead of buffering until
   the device OOMs. A query failure is delivered to that session's
   future as a typed :class:`QueryFailed` — the worker, the gang, and
   every other session keep serving (stage-not-task isolation, the Ray
   contrast of PAPERS §5).

Like telemetry, this module never *forces* an engine subsystem in:
every signal read goes through ``sys.modules.get`` (a subsystem that
was never imported simply contributes no signal), and the plan thunks
themselves pull in the engine on the worker thread.
"""

from __future__ import annotations

import itertools
import re
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future
from contextvars import ContextVar
from dataclasses import dataclass, fields as _dc_fields
from typing import Callable, Dict, List, Optional

from bodo_tpu.config import config
from bodo_tpu.utils.logging import log

_STORM_SIGS_MAX = 8       # storm signatures remembered per session
_EWMA_ALPHA = 0.5         # weight of the newest query in session EWMAs
_SIGNAL_TTL_S = 0.2       # local_signals() snapshot reuse window


# --------------------------------------------------------------------------
# typed backpressure contract
# --------------------------------------------------------------------------

class ServeRejection(RuntimeError):
    """Base of every admission rejection: carries the machine-readable
    reason and a retry-after hint (seconds) for the client's backoff."""

    kind = "rejected"

    def __init__(self, msg: str, *, retry_after_s: float = 0.0,
                 reason: str = ""):
        super().__init__(msg)
        self.retry_after_s = max(float(retry_after_s), 0.0)
        self.reason = reason or self.kind


class Overloaded(ServeRejection):
    """Shed: the gang cannot take more work right now (governor
    pressure, cache pressure, or a full queue). Retry after the hint."""

    kind = "overloaded"


class Degraded(ServeRejection):
    """The gang is unhealthy (dead/hung ranks). Sessions that did not
    opt into degraded service are rejected until it recovers."""

    kind = "degraded"


class BackOff(ServeRejection):
    """This session specifically should slow down (its shape churn is
    storming the compile cache, or it is comm-dominated on a skewed
    gang) — other sessions are still being admitted."""

    kind = "backoff"


class QueryFailed(RuntimeError):
    """A submitted query raised: delivered to THAT session's future with
    the original error chained, never to the worker or other sessions."""

    def __init__(self, session_id: str, query_id: Optional[str],
                 cause: BaseException):
        super().__init__(
            f"session {session_id!r} query {query_id or '-'} failed: "
            f"{type(cause).__name__}: {cause}")
        self.session_id = session_id
        self.query_id = query_id
        self.__cause__ = cause


# --------------------------------------------------------------------------
# admission signals: one structure, three producers
# --------------------------------------------------------------------------

@dataclass
class AdmissionSignals:
    """Normalized admission inputs. Every field is Optional — a parser
    fills what its payload carries and ``merged()`` overlays sources
    (e.g. /healthz gang state + /metrics governor occupancy)."""

    gang_status: Optional[str] = None
    unhealthy_ranks: Optional[tuple] = None
    governor_budget_bytes: Optional[int] = None
    governor_granted_bytes: Optional[int] = None
    governor_occupancy: Optional[float] = None
    oom_retries: Optional[int] = None
    comm_wait_frac: Optional[float] = None
    comm_max_wait_site: Optional[str] = None
    storm_signature: Optional[str] = None
    storm_compiles: Optional[int] = None
    storm_window_s: Optional[float] = None
    xla_budget_remaining: Optional[int] = None
    result_cache_occupancy: Optional[float] = None
    result_cache_pressure_sheds: Optional[int] = None
    # static HBM peak (progcheck liveness sweep) of the largest verified
    # program: admission sheds BEFORE trace when even the biggest known
    # program wouldn't fit the governor's remaining headroom
    progcheck_hbm_peak_bytes: Optional[int] = None
    # elastic capacity: <1.0 when the gang shrank after a rank loss —
    # the fleet admission twin scales the per-gang session quota (and
    # routing weight) by this instead of rejecting outright
    gang_capacity_frac: Optional[float] = None
    elastic_epoch: Optional[int] = None
    source: str = "local"

    def merged(self, other: "AdmissionSignals") -> "AdmissionSignals":
        """New signals with ``other``'s non-None fields overlaid."""
        out = AdmissionSignals(**{f.name: getattr(self, f.name)
                                  for f in _dc_fields(AdmissionSignals)})
        for f in _dc_fields(AdmissionSignals):
            v = getattr(other, f.name)
            if v is not None and f.name != "source":
                setattr(out, f.name, v)
        out.source = f"{self.source}+{other.source}"
        return out


def signals_from_health(doc: dict) -> AdmissionSignals:
    """Parse a /healthz JSON document (telemetry.health()) into
    admission signals: gang status + unhealthy ranks, the comm skew
    head, the recompile-storm flag, and the result-cache pressure block
    this PR adds to the document."""
    sig = AdmissionSignals(source="healthz")
    sig.gang_status = doc.get("status")
    bad = doc.get("unhealthy_ranks")
    if bad:
        sig.unhealthy_ranks = tuple(int(r) for r in bad)
    cm = doc.get("comm") or {}
    if "wait_frac" in cm:
        sig.comm_wait_frac = float(cm["wait_frac"])
        sig.comm_max_wait_site = cm.get("max_wait_site")
    st = doc.get("xla_recompile_storm") or {}
    if st.get("signature"):
        sig.storm_signature = str(st["signature"])
        sig.storm_compiles = int(st.get("compiles_in_window", 0))
        sig.storm_window_s = float(st.get("window_s", 0.0))
    rc = doc.get("result_cache") or {}
    if rc:
        if "occupancy_frac" in rc:
            sig.result_cache_occupancy = float(rc["occupancy_frac"])
        if "pressure_sheds" in rc:
            sig.result_cache_pressure_sheds = int(rc["pressure_sheds"])
    el = doc.get("elastic") or {}
    if "capacity_frac" in el:
        sig.gang_capacity_frac = float(el["capacity_frac"])
        sig.elastic_epoch = int(el.get("epoch", 0))
    return sig


_PROM_LINE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_PROM_LABEL = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _prom_samples(text: str):
    """Yield (name, labels, value) from Prometheus exposition text."""
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group(3))
        except ValueError:
            continue
        labels = dict(_PROM_LABEL.findall(m.group(2) or ""))
        yield m.group(1), labels, value


def signals_from_metrics(text: str) -> AdmissionSignals:
    """Parse /metrics Prometheus text into admission signals: governor
    occupancy (granted / derived budget) and OOM retries, the comm wait
    fraction, compile-budget headroom, and result-cache occupancy from
    the cache byte/budget gauges + pressure-shed counter."""
    sig = AdmissionSignals(source="metrics")
    granted = 0
    saw_granted = False
    rc_dev = rc_budget = None
    for name, labels, value in _prom_samples(text):
        if name == "bodo_tpu_mem_derived_budget_bytes":
            sig.governor_budget_bytes = int(value)
        elif name == "bodo_tpu_mem_operator_bytes" \
                and labels.get("kind") == "granted":
            granted += int(value)
            saw_granted = True
        elif name == "bodo_tpu_mem_oom_retries_total":
            sig.oom_retries = int(value)
        elif name == "bodo_tpu_comm_wait_frac":
            sig.comm_wait_frac = float(value)
        elif name == "bodo_tpu_xla_budget_remaining":
            sig.xla_budget_remaining = int(value)
        elif name == "bodo_tpu_result_cache_bytes" \
                and labels.get("tier") == "device":
            rc_dev = int(value)
        elif name == "bodo_tpu_result_cache_budget_bytes":
            rc_budget = int(value)
        elif name == "bodo_tpu_result_cache_events_total" \
                and labels.get("event") == "pressure_sheds":
            sig.result_cache_pressure_sheds = int(value)
    if saw_granted:
        sig.governor_granted_bytes = granted
    if sig.governor_budget_bytes and saw_granted:
        sig.governor_occupancy = granted / sig.governor_budget_bytes
    if rc_dev is not None and rc_budget:
        sig.result_cache_occupancy = rc_dev / rc_budget
    return sig


def _mod(name: str):
    return sys.modules.get(name)


def _maintenance_due() -> bool:
    """True when the view signature watcher wants a poll slot. Called
    by idle workers while HOLDING the scheduler lock, so it must stay
    lock-free (plain attribute reads in runtime/views.py) and cheap."""
    vw = _mod("bodo_tpu.runtime.views")
    if vw is None:
        return False
    try:
        return vw.maintenance_due()
    except Exception:  # noqa: BLE001 - a broken watcher must not wedge
        return False


def _run_maintenance_tick(sched) -> None:
    """One watcher poll: detect changed base tables and schedule view
    refreshes as weighted-fair work on the system maintenance session."""
    vw = _mod("bodo_tpu.runtime.views")
    if vw is not None:
        try:
            vw.maintenance_tick(sched)
        except Exception:  # noqa: BLE001
            pass


def local_signals() -> AdmissionSignals:
    """In-process signals: the same document /healthz serves, plus a
    direct governor read (occupancy without a /metrics scrape). Every
    subsystem is read via sys.modules.get — an admission check never
    forces a jax import."""
    sig = AdmissionSignals(source="local")
    tl = _mod("bodo_tpu.runtime.telemetry")
    if tl is not None:
        try:
            sig = signals_from_health(tl.health())
            sig.source = "local"
        except Exception:  # noqa: BLE001 - admission reads best-effort
            pass
    mg = _mod("bodo_tpu.runtime.memory_governor")
    if mg is not None:
        try:
            st = mg.governor().stats()
            budget = int(st.get("derived_budget_bytes", 0))
            granted = int(sum(m.get("granted", 0)
                              for m in st.get("operators", {}).values()))
            sig.governor_budget_bytes = budget
            sig.governor_granted_bytes = granted
            if budget > 0:
                sig.governor_occupancy = granted / budget
            sig.oom_retries = int(st.get("n_oom_retries", 0))
        except Exception:  # noqa: BLE001
            pass
    pc = _mod("bodo_tpu.analysis.progcheck")
    if pc is not None:
        try:
            est = int(pc.max_hbm_estimate())
            if est > 0:
                sig.progcheck_hbm_peak_bytes = est
        except Exception:  # noqa: BLE001
            pass
    rc = _mod("bodo_tpu.runtime.result_cache")
    if rc is not None and sig.result_cache_occupancy is None:
        try:
            rs = rc.stats()
            budget = int(rs.get("budget_bytes", 0))
            if budget > 0:
                sig.result_cache_occupancy = \
                    int(rs.get("device_bytes", 0)) / budget
            sig.result_cache_pressure_sheds = \
                int(rs.get("pressure_sheds", 0))
        except Exception:  # noqa: BLE001
            pass
    return sig


# --------------------------------------------------------------------------
# admission controller
# --------------------------------------------------------------------------

@dataclass
class Decision:
    action: str                    # "admit" | "shed" | "degrade" | "backoff"
    reason: str = ""
    retry_after_s: float = 0.0


class AdmissionController:
    """Stateless-per-session decision function over AdmissionSignals,
    with one piece of memory: the last-seen OOM-retry / pressure-shed
    counters, so a NEW retry or shed since the previous decision reads
    as live memory pressure (the counters themselves are cumulative)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._last_oom: Optional[int] = None
        self._last_sheds: Optional[int] = None

    def _pressure_event(self, sig: AdmissionSignals) -> Optional[str]:
        with self._mu:
            out = None
            if sig.oom_retries is not None:
                if self._last_oom is not None \
                        and sig.oom_retries > self._last_oom:
                    out = "oom_retry"
                self._last_oom = sig.oom_retries
            if sig.result_cache_pressure_sheds is not None:
                if self._last_sheds is not None \
                        and sig.result_cache_pressure_sheds > \
                        self._last_sheds:
                    out = out or "cache_pressure_shed"
                self._last_sheds = sig.result_cache_pressure_sheds
            return out

    def decide(self, sig: AdmissionSignals,
               session: Optional["Session"] = None) -> Decision:
        base = max(float(config.serve_retry_after_s), 0.05)
        if not config.serve_admission:
            return Decision("admit", "admission_disabled")
        # 1) shed on memory pressure: the whole point of admission is
        #    that overload turns into a typed rejection, never an OOM
        occ = sig.governor_occupancy
        if occ is not None and occ >= float(config.serve_shed_occupancy):
            return Decision("shed", f"governor_occupancy={occ:.2f}",
                            retry_after_s=base * 4)
        pressure = self._pressure_event(sig)
        if pressure is not None:
            return Decision("shed", pressure, retry_after_s=base * 4)
        # 1b) shed BEFORE trace when the statically-estimated peak of
        #     the gang's largest verified program exceeds the governor's
        #     remaining headroom: the query would compile, dispatch and
        #     only then discover the pressure mid-flight
        est = sig.progcheck_hbm_peak_bytes
        if est and sig.governor_budget_bytes:
            headroom = sig.governor_budget_bytes \
                - int(sig.governor_granted_bytes or 0)
            if est > headroom > 0 or headroom <= 0:
                return Decision(
                    "shed",
                    f"progcheck_hbm_estimate={est}>headroom={headroom}",
                    retry_after_s=base * 4)
        # 2) degrade on gang health: dead/hung ranks mean sharded
        #    results are at risk — only opted-in sessions proceed
        if sig.unhealthy_ranks:
            if session is None or not session.allow_degraded:
                return Decision(
                    "degrade",
                    f"unhealthy_ranks={list(sig.unhealthy_ranks)}",
                    retry_after_s=base * 2)
        # 3) back off the storm owner: a session whose shape churn is
        #    recompiling every dispatch must not evict other tenants'
        #    executables (attribution: the session saw compiles land
        #    under this signature during its own queries)
        if sig.storm_signature and session is not None \
                and session.owns_storm(sig.storm_signature):
            return Decision(
                "backoff", f"recompile_storm={sig.storm_signature}",
                retry_after_s=max(base * 2,
                                  float(sig.storm_window_s or 0.0)))
        # 4) back off comm-dominated sessions on a skewed gang: more of
        #    their queries just means more peer-wait for everyone
        thresh = float(config.serve_comm_wait_frac)
        if sig.comm_wait_frac is not None \
                and sig.comm_wait_frac >= thresh \
                and session is not None \
                and session.ewma_comm_wait_frac >= thresh:
            return Decision(
                "backoff",
                f"comm_skew={sig.comm_wait_frac:.2f}"
                f"@{sig.comm_max_wait_site or '-'}",
                retry_after_s=base * 2)
        return Decision("admit", "ok")


# --------------------------------------------------------------------------
# sessions
# --------------------------------------------------------------------------

class _Request:
    __slots__ = ("session", "fn", "future", "enq_ts", "query_id")

    def __init__(self, session: "Session", fn: Callable):
        self.session = session
        self.fn = fn
        self.future: Future = Future()
        self.enq_ts = time.monotonic()
        self.query_id: Optional[str] = None


class Session:
    """One tenant's handle on the resident gang. Mutable state is
    guarded by the owning scheduler's lock; the EWMA/storm fields are
    only written by worker threads between queries."""

    def __init__(self, sched: "Scheduler", sid: str, *,
                 priority: float = 1.0, allow_degraded: bool = False,
                 slo: str = "throughput"):
        self._sched = sched
        self.sid = sid
        self.weight = max(float(priority), 0.01)
        self.allow_degraded = bool(allow_degraded)
        # SLO class: "latency" sessions age faster in _rank_locked so
        # their queued head overtakes throughput-bound traffic.
        self.slo = slo if slo in ("latency", "throughput") \
            else "throughput"
        self.queue: deque = deque()
        self.vtime = 0.0              # served seconds / weight
        self.served_s = 0.0
        self.ewma_query_s = 0.0
        self.ewma_comm_wait_frac = 0.0
        self._storm_sigs: deque = deque(maxlen=_STORM_SIGS_MAX)
        self.counters: Dict[str, int] = {}
        self.closed = False

    # -- client surface ----------------------------------------------------

    def submit(self, fn: Callable) -> Future:
        """Queue a plan thunk; returns a Future resolving to its result
        (or raising QueryFailed / a typed rejection synchronously)."""
        return self._sched.submit(self, fn)

    def run(self, fn: Callable, timeout: Optional[float] = None):
        """Submit and block for the result."""
        return self.submit(fn).result(timeout=timeout)

    def subscribe(self, view: str,
                  max_staleness_s: Optional[float] = None):
        """Register a standing query against a materialized view
        (runtime/views.py): returns a Subscription whose ``next()``
        delivers every refreshed result through an ordinary serve
        future. The refresh work itself runs on the system maintenance
        session, not billed to this tenant; ``max_staleness_s`` bounds
        how far behind a base-table change the delivered result may be
        (it tightens the scheduler's signature poll interval)."""
        from bodo_tpu.runtime import views as _views
        return _views.subscribe(view, session=self,
                                max_staleness_s=max_staleness_s)

    def close(self) -> None:
        self._sched.close_session(self)

    def stats(self) -> dict:
        with self._sched._cv:
            return {
                "session": self.sid,
                "weight": self.weight,
                "slo": self.slo,
                "allow_degraded": self.allow_degraded,
                "queued": len(self.queue),
                "vtime_s": round(self.vtime, 6),
                "served_s": round(self.served_s, 6),
                "ewma_query_s": round(self.ewma_query_s, 6),
                "ewma_comm_wait_frac":
                    round(self.ewma_comm_wait_frac, 4),
                "storm_signatures": list(self._storm_sigs),
                "counters": dict(self.counters),
                "closed": self.closed,
            }

    # -- scheduler-side helpers -------------------------------------------

    def owns_storm(self, signature: str) -> bool:
        return signature in self._storm_sigs

    def note_storm(self, signature: str) -> None:
        if signature and signature not in self._storm_sigs:
            self._storm_sigs.append(signature)

    def _count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n


# --------------------------------------------------------------------------
# the scheduler
# --------------------------------------------------------------------------

class Scheduler:
    """Weighted fair queueing + admission over a worker pool that is
    the only thing actually executing plans on the gang."""

    def __init__(self):
        self._cv = threading.Condition()
        self._sessions: Dict[str, Session] = {}
        self._workers: List[threading.Thread] = []
        self._stop = threading.Event()
        self._pending = 0
        self._running = 0
        self._decisions: Dict[str, int] = {}
        self._completed = 0
        self._failed = 0
        self._resumed = 0
        self._sig_cache: Optional[AdmissionSignals] = None
        self._sig_at = 0.0
        self._seq = itertools.count(1)
        self.admission = AdmissionController()

    # -- sessions ----------------------------------------------------------

    def session(self, session_id: Optional[str] = None, *,
                priority: float = 1.0,
                allow_degraded: bool = False,
                slo: str = "throughput") -> Session:
        """Open (or re-open) a session. Re-opening an existing id keeps
        its queue/accounting but re-applies priority/degraded/SLO."""
        with self._cv:
            sid = session_id or f"s{next(self._seq)}"
            s = self._sessions.get(sid)
            if s is None:
                s = Session(self, sid, priority=priority,
                            allow_degraded=allow_degraded, slo=slo)
                self._sessions[sid] = s
            else:
                s.weight = max(float(priority), 0.01)
                s.allow_degraded = bool(allow_degraded)
                s.slo = slo if slo in ("latency", "throughput") \
                    else "throughput"
                s.closed = False
            return s

    def close_session(self, session: Session) -> None:
        """Refuse new submits and drop queued (not yet running) work;
        queued futures get a typed rejection."""
        with self._cv:
            session.closed = True
            dropped = list(session.queue)
            session.queue.clear()
            self._pending -= len(dropped)
        for req in dropped:
            req.future.set_exception(Overloaded(
                f"session {session.sid!r} closed with queued work",
                reason="session_closed"))

    # -- submission / admission -------------------------------------------

    def _signals(self) -> AdmissionSignals:
        now = time.monotonic()
        with self._cv:
            if self._sig_cache is not None \
                    and now - self._sig_at < _SIGNAL_TTL_S:
                return self._sig_cache
        sig = local_signals()
        with self._cv:
            self._sig_cache, self._sig_at = sig, time.monotonic()
        return sig

    def _reject(self, session: Session, exc: ServeRejection):
        with self._cv:
            self._decisions[exc.kind] = \
                self._decisions.get(exc.kind, 0) + 1
            session._count(f"rejected_{exc.kind}")
        try:
            import os as _os

            from bodo_tpu.utils import metrics
            names = ("kind", "session")
            labels = {"kind": exc.kind, "session": session.sid}
            gid = _os.environ.get("BODO_TPU_GANG_ID", "")
            if gid:
                # fleet gang: per-gang attribution on the scraped
                # series (env is process-constant, so the label set
                # never flips mid-registry)
                names += ("gang",)
                labels["gang"] = gid
            metrics.counter(
                "bodo_tpu_serve_rejections_total",
                "admission/backpressure rejections by kind",
                names).labels(**labels).inc()
        except Exception:  # noqa: BLE001
            pass
        raise exc

    def submit(self, session: Session, fn: Callable) -> Future:
        if session.closed:
            self._reject(session, Overloaded(
                f"session {session.sid!r} is closed",
                reason="session_closed"))
        decision = self.admission.decide(self._signals(), session)
        if decision.action != "admit":
            exc_type = {"shed": Overloaded, "degrade": Degraded,
                        "backoff": BackOff}[decision.action]
            self._reject(session, exc_type(
                f"{decision.action}: {decision.reason}",
                retry_after_s=decision.retry_after_s,
                reason=decision.reason))
        ewma = max(session.ewma_query_s, 0.01)
        with self._cv:
            depth = max(int(config.serve_queue_depth), 1)
            if len(session.queue) >= depth:
                hint = ewma * (len(session.queue) + 1)
            elif self._pending >= max(int(config.serve_max_pending), 1):
                hint = ewma * (self._pending + 1) \
                    / max(len(self._workers), 1)
            else:
                hint = None
                self._decisions["admit"] = \
                    self._decisions.get("admit", 0) + 1
                if not session.queue:
                    # a session returning from idle rejoins at the
                    # backlog's minimum virtual time: it competes
                    # fairly from now on instead of replaying the
                    # service it never consumed while away
                    floor = [t.vtime for t in self._sessions.values()
                             if t.queue]
                    if floor:
                        session.vtime = max(session.vtime, min(floor))
                req = _Request(session, fn)
                session.queue.append(req)
                self._pending += 1
                session._count("submitted")
                self._cv.notify()
        if hint is not None:
            self._reject(session, Overloaded(
                f"session {session.sid!r} queue full "
                f"({len(session.queue)} queued)",
                retry_after_s=hint, reason="queue_full"))
        self._ensure_workers()
        return req.future

    # -- fair-share pick ---------------------------------------------------

    def _rank_locked(self, s: Session, now: float) -> float:
        """Virtual-time rank with priority aging: every serve_aging_s
        seconds the head request has waited discounts one second of
        accrued virtual time, so starvation is bounded. Latency-class
        sessions age serve_latency_boost× faster — their head overtakes
        queued throughput traffic without zeroing its progress."""
        aging = max(float(config.serve_aging_s), 0.01)
        if s.slo == "latency":
            aging /= max(float(config.serve_latency_boost), 1.0)
        waited = now - s.queue[0].enq_ts
        return s.vtime - waited / aging

    def _pick_locked(self) -> Optional[_Request]:
        now = time.monotonic()
        best = None
        for s in self._sessions.values():
            if not s.queue:
                continue
            r = self._rank_locked(s, now)
            if best is None or r < best[0] \
                    or (r == best[0] and s.sid < best[1].sid):
                best = (r, s)
        if best is None:
            return None
        s = best[1]
        req = s.queue.popleft()
        self._pending -= 1
        return req

    # -- workers -----------------------------------------------------------

    def _ensure_workers(self) -> None:
        with self._cv:
            want = max(int(config.serve_workers), 1)
            alive = [t for t in self._workers if t.is_alive()]
            self._workers = alive
            if self._stop.is_set():
                self._stop = threading.Event()
            stop = self._stop
            n_new = want - len(alive)
            new = []
            for _ in range(max(n_new, 0)):
                t = threading.Thread(
                    target=self._worker, args=(stop,),
                    name=f"bodo-tpu-serve-{len(self._workers) + len(new)}",
                    daemon=True)
                new.append(t)
                self._workers.append(t)
        for t in new:
            t.start()

    def _worker(self, stop: threading.Event) -> None:
        while True:
            tick = False
            with self._cv:
                req = None
                while not stop.is_set():
                    req = self._pick_locked()
                    if req is not None:
                        break
                    # between queue drains: the view signature watcher
                    # gets a poll slot. maintenance_due() is lock-free
                    # attribute reads — it must never block under _cv.
                    if _maintenance_due():
                        tick = True
                        break
                    self._cv.wait(0.1)
                if req is None and not tick:
                    return
                if req is not None:
                    self._running += 1
            if req is None:
                # the tick runs OUTSIDE the lock: view maintenance
                # submits refresh work back into this scheduler, which
                # re-acquires _cv
                _run_maintenance_tick(self)
                continue
            try:
                self._execute(req)
            finally:
                with self._cv:
                    self._running -= 1
                    self._cv.notify_all()

    # -- execution + per-session attribution ------------------------------

    def _execute(self, req: _Request) -> None:
        s = req.session
        token = _session_ctx.set(s.sid)
        grant = None
        comm0 = xla0 = None
        cm = _mod("bodo_tpu.parallel.comm")
        ob = _mod("bodo_tpu.runtime.xla_observatory")
        try:
            if comm0 is None and cm is not None:
                try:
                    comm0 = cm.stats()
                except Exception:  # noqa: BLE001
                    comm0 = None
            if ob is not None:
                try:
                    xla0 = ob.head()
                except Exception:  # noqa: BLE001
                    xla0 = None
            grant = self._session_grant(s)
            t0 = time.perf_counter()
            try:
                out, qid = self._run_in_span(req)
            except BaseException as e:  # noqa: BLE001 - typed delivery
                # the scheduler fails nothing it can resume: a rank
                # loss under an elastic gang re-runs the thunk ONCE on
                # the shrunk mesh — completed stages hit the result
                # cache, so only the plan suffix past the last stage
                # checkpoint actually executes again. Other sessions
                # never see the loss at all.
                out = None
                resumed = False
                el = _mod("bodo_tpu.runtime.elastic")
                if el is not None and config.elastic and \
                        el.is_resumable(e):
                    try:
                        out, qid = self._run_in_span(req)
                        resumed = True
                    except BaseException as e2:  # noqa: BLE001
                        e = e2
                if not resumed:
                    wall = time.perf_counter() - t0
                    self._account(s, wall, cm, comm0, ob, xla0)
                    with self._cv:
                        self._failed += 1
                        s._count("failed")
                    req.future.set_exception(
                        QueryFailed(s.sid, req.query_id, e))
                    return
                el.note_resume()
                with self._cv:
                    self._resumed += 1
                    s._count("resumed")
            wall = time.perf_counter() - t0
            self._account(s, wall, cm, comm0, ob, xla0)
            with self._cv:
                self._completed += 1
                s._count("completed")
            req.future.set_result(out)
            # background grow: a shrunk gang re-admits replacement
            # capacity at the next query boundary
            el = _mod("bodo_tpu.runtime.elastic")
            if el is not None:
                try:
                    el.note_query_boundary()
                except Exception:  # noqa: BLE001
                    pass
        finally:
            if grant is not None:
                try:
                    grant.release()
                except Exception:  # noqa: BLE001
                    pass
            _session_ctx.reset(token)

    def _run_in_span(self, req: _Request):
        """Execute the thunk under a tracing query span (when tracing is
        on) so EXPLAIN/trace records carry the query id the session tag
        attaches to."""
        tr = _mod("bodo_tpu.utils.tracing")
        if tr is not None:
            try:
                if tr.is_tracing() and tr.current_query_id() is None:
                    with tr.query_span() as qid:
                        req.query_id = qid
                        return req.fn(), qid
            except ServeRejection:
                raise
            except Exception:  # noqa: BLE001 - span plumbing only
                pass
        return req.fn(), req.query_id

    def _session_grant(self, s: Session):
        """Partitioned governor accounting: while a session's query
        runs it holds a small named grant (``session:<sid>``) so the
        governor's operator table shows who is on the gang; enforcement
        stays with the per-operator grants and the cache's fair share
        (a large reservation here would double-charge the same bytes)."""
        if not config.mem_governor:
            return None
        mg = _mod("bodo_tpu.runtime.memory_governor")
        if mg is None:
            return None
        try:
            return mg.governor().admit(f"session:{s.sid}", want=1,
                                       wait=False)
        except Exception:  # noqa: BLE001 - accounting is best-effort
            return None

    def _account(self, s: Session, wall: float, cm, comm0, ob,
                 xla0) -> None:
        """Post-query attribution: virtual time for fair share, EWMAs
        for the backoff rules, storm-signature ownership."""
        wall = max(wall, 0.0)
        frac = None
        if cm is not None and comm0 is not None:
            try:
                after = cm.stats()
                wait = after["wait_s"] - comm0["wait_s"]
                frac = min(max(wait / wall, 0.0), 1.0) if wall > 1e-9 \
                    else 0.0
            except Exception:  # noqa: BLE001
                frac = None
        storm_sig = None
        if ob is not None and xla0 is not None:
            try:
                head = ob.head()
                if head["compiles"] - xla0["compiles"] > 0:
                    st = ob.storm()
                    if st["storming"]:
                        storm_sig = st["signature"]
            except Exception:  # noqa: BLE001
                storm_sig = None
        with self._cv:
            s.vtime += wall / s.weight
            s.served_s += wall
            a = _EWMA_ALPHA
            s.ewma_query_s = wall if s.ewma_query_s == 0.0 \
                else (1 - a) * s.ewma_query_s + a * wall
            if frac is not None:
                s.ewma_comm_wait_frac = \
                    (1 - a) * s.ewma_comm_wait_frac + a * frac
            if storm_sig:
                s.note_storm(storm_sig)

    # -- lifecycle / introspection ----------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every queued/running request finished (True) or
        the timeout expired (False)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending > 0 or self._running > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.1))
        return True

    def stop(self) -> None:
        """Stop the worker pool; queued work stays queued and resumes
        on the next submit (which restarts workers)."""
        with self._cv:
            stop = self._stop
            workers = list(self._workers)
            self._workers = []
        stop.set()
        with self._cv:
            self._cv.notify_all()
        for t in workers:
            if t.is_alive():
                t.join(timeout=2.0)

    def reset(self) -> None:
        """Tests: stop workers, fail queued futures, drop sessions."""
        self.stop()
        with self._cv:
            sessions = list(self._sessions.values())
            self._sessions.clear()
            self._pending = 0
            self._decisions.clear()
            self._completed = 0
            self._failed = 0
            self._resumed = 0
            self._sig_cache = None
        for s in sessions:
            for req in s.queue:
                req.future.set_exception(Overloaded(
                    "scheduler reset", reason="reset"))
            s.queue.clear()

    def reconfigure(self) -> None:
        """config.set_config hook: re-size the worker pool and drop the
        signal snapshot so new thresholds apply to the next submit."""
        with self._cv:
            self._sig_cache = None
        if self._workers:
            self._ensure_workers()

    def stats(self) -> dict:
        with self._cv:
            return {
                "sessions": len(self._sessions),
                "queued": self._pending,
                "running": self._running,
                "workers": len([t for t in self._workers
                                if t.is_alive()]),
                "completed": self._completed,
                "failed": self._failed,
                "resumed": self._resumed,
                "decisions": dict(self._decisions),
                "by_session": {sid: {
                    "queued": len(s.queue),
                    "weight": s.weight,
                    "vtime_s": round(s.vtime, 6),
                    "served_s": round(s.served_s, 6),
                    "ewma_query_s": round(s.ewma_query_s, 6),
                    "ewma_comm_wait_frac":
                        round(s.ewma_comm_wait_frac, 4),
                    "counters": dict(s.counters),
                } for sid, s in sorted(self._sessions.items())},
            }


# --------------------------------------------------------------------------
# module singleton + session context
# --------------------------------------------------------------------------

# the executing query's session id; worker threads set it around the
# thunk, so everything under plan/physical.execute can attribute
_session_ctx: ContextVar = ContextVar("bodo_tpu_session", default=None)

_scheduler: Optional[Scheduler] = None
_sched_mu = threading.Lock()


def scheduler() -> Scheduler:
    global _scheduler
    with _sched_mu:
        if _scheduler is None:
            _scheduler = Scheduler()
        return _scheduler


def current_session() -> Optional[str]:
    """Session id of the executing query, or None outside the serving
    layer (single-tenant callers behave exactly as before). Lower
    layers read this via sys.modules.get — never import-forcing."""
    return _session_ctx.get()


class session_scope:
    """Attribute work on the CALLING thread to a session (tests, bench
    clients that bypass the worker pool)."""

    def __init__(self, sid: str):
        self.sid = sid
        self._token = None

    def __enter__(self):
        self._token = _session_ctx.set(self.sid)
        return self.sid

    def __exit__(self, *exc):
        _session_ctx.reset(self._token)
        return False


def reconfigure() -> None:
    """config.set_config hook (serve_* keys)."""
    with _sched_mu:
        sched = _scheduler
    if sched is not None:
        sched.reconfigure()


def reset() -> None:
    """Tests: tear down the singleton scheduler."""
    global _scheduler
    with _sched_mu:
        sched, _scheduler = _scheduler, None
    if sched is not None:
        sched.reset()


def stats() -> Optional[dict]:
    """Live scheduler stats, or None when no scheduler was created —
    telemetry/metrics read through this (lazily, via sys.modules.get)."""
    with _sched_mu:
        sched = _scheduler
    return sched.stats() if sched is not None else None
