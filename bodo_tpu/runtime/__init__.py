"""Native host runtime: buffer pool + spill manager (C++ via ctypes).

See host_pool.cpp — the analogue of the reference's bodo::BufferPool and
StorageManager for the host staging side. Built on demand with the system
compiler; `has_native_pool()` reports availability (clean fallback when no
toolchain exists).
"""

from bodo_tpu.runtime.pool import (HostBufferPool, PooledBuffer,
                                   default_pool, has_native_pool)

__all__ = ["HostBufferPool", "PooledBuffer", "default_pool",
           "has_native_pool"]
