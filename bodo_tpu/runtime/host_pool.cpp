// Host buffer pool + spill manager for table staging.
//
// TPU-native analogue of the reference engine's C++ memory runtime:
//   - BufferPool: size-class mmap allocator with pin/unpin semantics
//     (reference: bodo/libs/_memory.h:632 bodo::BufferPool, SizeClass :240)
//   - StorageManager: spills unpinned frames to local disk and restores
//     them on demand (reference: bodo/libs/_storage_manager.h:116)
//
// On TPU the device side is owned by XLA's allocator, so this pool manages
// the *host* staging side: Arrow ingest buffers, gather/shard scratch, and
// larger-than-RAM spill of staged partitions. Exposed to Python via a flat
// C ABI (ctypes — no pybind11 dependency).
//
// Memory layout: allocations are served from mmap'd size-class frames
// (powers of two from 64 KiB to 1 GiB). Small allocations (< 64 KiB) pass
// through to malloc (reference: BufferPoolOptions.malloc_threshold).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMinFrame = 64 * 1024;          // smallest size class
constexpr uint64_t kMaxFrame = 1ULL << 30;         // largest size class
constexpr uint64_t kMallocThreshold = 64 * 1024;   // below: plain malloc

struct Frame {
  void* addr = nullptr;        // mmap'd region (nullptr while spilled)
  uint64_t size = 0;           // size-class bytes
  uint64_t used = 0;           // requested bytes
  int32_t pins = 1;            // pin count; 0 => spillable
  bool spilled = false;
  std::string spill_path;
};

struct PoolStats {
  std::atomic<uint64_t> bytes_allocated{0};
  std::atomic<uint64_t> bytes_in_use{0};
  std::atomic<uint64_t> bytes_spilled{0};
  std::atomic<uint64_t> n_allocs{0};
  std::atomic<uint64_t> n_spills{0};
  std::atomic<uint64_t> n_restores{0};
  // allocations that pushed bytes_in_use past the limit after spilling
  // failed to make room (no spill dir / everything pinned) — the limit is
  // enforced best-effort, but overcommit is observable, not silent
  std::atomic<uint64_t> n_overcommits{0};
};

class BufferPool {
 public:
  explicit BufferPool(uint64_t limit_bytes, const char* spill_dir)
      : limit_(limit_bytes), spill_dir_(spill_dir ? spill_dir : "") {}

  ~BufferPool() {
    std::lock_guard<std::mutex> g(mu_);
    for (auto& [id, f] : frames_) {
      if (f.addr) munmap(f.addr, f.size);
      if (!f.spill_path.empty()) unlink(f.spill_path.c_str());
    }
  }

  // Returns a handle id (>0) or 0 on failure. *out receives the pointer.
  int64_t Allocate(uint64_t nbytes, void** out) {
    uint64_t size = SizeClass(nbytes);
    std::lock_guard<std::mutex> g(mu_);
    if (stats_.bytes_in_use.load() + size > limit_ && !spill_dir_.empty()) {
      SpillUntil(size);  // best effort
    }
    if (stats_.bytes_in_use.load() + size > limit_) {
      stats_.n_overcommits += 1;
    }
    void* p = mmap(nullptr, size, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (p == MAP_FAILED) return 0;
    int64_t id = next_id_++;
    Frame f;
    f.addr = p;
    f.size = size;
    f.used = nbytes;
    frames_[id] = f;
    stats_.bytes_allocated += size;
    stats_.bytes_in_use += size;
    stats_.n_allocs += 1;
    *out = p;
    return id;
  }

  int Free(int64_t id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = frames_.find(id);
    if (it == frames_.end()) return -1;
    Frame& f = it->second;
    if (f.addr) {
      munmap(f.addr, f.size);
      stats_.bytes_in_use -= f.size;
    }
    if (f.spilled) stats_.bytes_spilled -= f.used;
    if (!f.spill_path.empty()) unlink(f.spill_path.c_str());
    stats_.bytes_allocated -= f.size;
    frames_.erase(it);
    return 0;
  }

  int Pin(int64_t id, void** out) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = frames_.find(id);
    if (it == frames_.end()) return -1;
    Frame& f = it->second;
    if (f.spilled) {
      if (Restore(f) != 0) return -2;
    }
    f.pins++;
    *out = f.addr;
    return 0;
  }

  int Unpin(int64_t id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = frames_.find(id);
    if (it == frames_.end()) return -1;
    if (it->second.pins > 0) it->second.pins--;
    return 0;
  }

  // Explicitly spill one unpinned frame to disk. Returns 0 on success.
  int Spill(int64_t id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = frames_.find(id);
    if (it == frames_.end()) return -1;
    return SpillFrame(id, it->second);
  }

  void Stats(uint64_t* out8) {
    out8[0] = stats_.bytes_allocated.load();
    out8[1] = stats_.bytes_in_use.load();
    out8[2] = stats_.bytes_spilled.load();
    out8[3] = stats_.n_allocs.load();
    out8[4] = stats_.n_spills.load();
    out8[5] = stats_.n_restores.load();
    out8[6] = stats_.n_overcommits.load();
    uint64_t in_use = stats_.bytes_in_use.load();
    out8[7] = in_use > limit_ ? in_use - limit_ : 0;
  }

 private:
  static uint64_t SizeClass(uint64_t n) {
    uint64_t s = kMinFrame;
    while (s < n && s < kMaxFrame) s <<= 1;
    return std::max(s, ((n + 4095) / 4096) * 4096);
  }

  int SpillFrame(int64_t id, Frame& f) {
    if (f.spilled || f.pins > 0 || spill_dir_.empty() || !f.addr) return -3;
    std::string path =
        spill_dir_ + "/frame_" + std::to_string(id) + ".spill";
    int fd = open(path.c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0600);
    if (fd < 0) return -4;
    uint64_t off = 0;
    const char* src = static_cast<const char*>(f.addr);
    while (off < f.used) {
      ssize_t w = write(fd, src + off, f.used - off);
      if (w <= 0) {
        close(fd);
        unlink(path.c_str());
        return -5;
      }
      off += static_cast<uint64_t>(w);
    }
    close(fd);
    munmap(f.addr, f.size);
    f.addr = nullptr;
    f.spilled = true;
    f.spill_path = path;
    stats_.bytes_in_use -= f.size;
    stats_.bytes_spilled += f.used;
    stats_.n_spills += 1;
    return 0;
  }

  int Restore(Frame& f) {
    void* p = mmap(nullptr, f.size, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (p == MAP_FAILED) return -1;
    int fd = open(f.spill_path.c_str(), O_RDONLY);
    if (fd < 0) {
      munmap(p, f.size);
      return -2;
    }
    uint64_t off = 0;
    char* dst = static_cast<char*>(p);
    while (off < f.used) {
      ssize_t r = read(fd, dst + off, f.used - off);
      if (r <= 0) {
        close(fd);
        munmap(p, f.size);
        return -3;
      }
      off += static_cast<uint64_t>(r);
    }
    close(fd);
    unlink(f.spill_path.c_str());
    f.spill_path.clear();
    f.addr = p;
    f.spilled = false;
    stats_.bytes_in_use += f.size;
    stats_.bytes_spilled -= f.used;
    stats_.n_restores += 1;
    return 0;
  }

  void SpillUntil(uint64_t need) {
    // evict unpinned frames (largest first) until `need` fits
    std::vector<std::pair<uint64_t, int64_t>> candidates;
    for (auto& [id, f] : frames_) {
      if (f.pins == 0 && !f.spilled && f.addr) {
        candidates.push_back({f.size, id});
      }
    }
    std::sort(candidates.rbegin(), candidates.rend());
    for (auto& [sz, id] : candidates) {
      if (stats_.bytes_in_use.load() + need <= limit_) break;
      SpillFrame(id, frames_[id]);
    }
  }

  std::mutex mu_;
  uint64_t limit_;
  std::string spill_dir_;
  std::unordered_map<int64_t, Frame> frames_;
  int64_t next_id_ = 1;
  PoolStats stats_;
};

}  // namespace

extern "C" {

void* btpu_pool_create(uint64_t limit_bytes, const char* spill_dir) {
  return new BufferPool(limit_bytes, spill_dir);
}

void btpu_pool_destroy(void* pool) { delete static_cast<BufferPool*>(pool); }

int64_t btpu_alloc(void* pool, uint64_t nbytes, void** out) {
  return static_cast<BufferPool*>(pool)->Allocate(nbytes, out);
}

int btpu_free(void* pool, int64_t id) {
  return static_cast<BufferPool*>(pool)->Free(id);
}

int btpu_pin(void* pool, int64_t id, void** out) {
  return static_cast<BufferPool*>(pool)->Pin(id, out);
}

int btpu_unpin(void* pool, int64_t id) {
  return static_cast<BufferPool*>(pool)->Unpin(id);
}

int btpu_spill(void* pool, int64_t id) {
  return static_cast<BufferPool*>(pool)->Spill(id);
}

void btpu_stats(void* pool, uint64_t* out8) {
  static_cast<BufferPool*>(pool)->Stats(out8);
}

}  // extern "C"
