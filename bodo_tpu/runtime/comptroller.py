"""Operator memory comptroller: per-operator budgets over the host pool.

TPU-native analogue of the reference's OperatorComptroller +
OperatorBufferPool (reference: bodo/libs/memory_budget.py:28
OperatorComptroller, bodo/libs/_operator_pool.h OperatorBufferPool).
Where the reference threads budget hints through its C++ streaming
operators, here every streaming operator that parks state in the native
host pool registers with the comptroller; on allocation pressure the
comptroller spills the LARGEST unpinned parked state first (best
bytes-freed-per-restore-cost policy) and records the event in the
tracing profile, instead of leaving eviction order to the pool's
arbitrary scan.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from bodo_tpu.runtime.offload import OffloadedTable, offload_table
from bodo_tpu.runtime.pool import HostBufferPool, default_pool
from bodo_tpu.table.table import Table
from bodo_tpu.utils.logging import log


class OperatorComptroller:
    """Arbitrates host-pool bytes across concurrently-running operators.

    Operators register(), then park()/release() spillable state. When a
    park would push the pool past its limit, the largest unpinned parked
    state (any operator) spills to disk first — so one operator's build
    side can't starve another's accumulation."""

    def __init__(self, pool: Optional[HostBufferPool] = None,
                 limit_bytes: Optional[int] = None):
        self.pool = pool or default_pool()
        self.limit = limit_bytes if limit_bytes is not None else \
            getattr(self.pool, "limit_bytes", 4 << 30)
        self._mu = threading.Lock()
        self._next_op = 1
        self._ops: Dict[int, str] = {}
        # op_id -> list of (OffloadedTable, nbytes, spilled?)
        self._parked: Dict[int, List] = {}
        self.n_spills = 0
        self.bytes_spilled = 0

    # -- registration -------------------------------------------------------

    def register(self, name: str) -> int:
        with self._mu:
            op = self._next_op
            self._next_op += 1
            self._ops[op] = name
            self._parked[op] = []
            return op

    def unregister(self, op_id: int) -> None:
        with self._mu:
            self._ops.pop(op_id, None)
            self._parked.pop(op_id, None)

    # -- parking ------------------------------------------------------------

    @staticmethod
    def _table_bytes(t: Table) -> int:
        n = 0
        for c in t.columns.values():
            n += c.data.size * c.data.dtype.itemsize
            if c.valid is not None:
                n += c.valid.size
        return n

    def park(self, op_id: int, t: Table) -> OffloadedTable:
        """Offload a table into the pool under this operator's account,
        making room by spilling other parked state if needed. If the
        pool is still over its limit after the insert (a single parked
        state bigger than the whole budget), the new state spills to
        disk immediately — parked state is always allowed to leave
        memory."""
        need = self._table_bytes(t)
        self.ensure_room(need)
        ot = offload_table(t, pool=self.pool)
        with self._mu:
            if op_id in self._parked:
                self._parked[op_id].append([ot, need, False])
        self.ensure_room(0)
        return ot

    def release(self, op_id: int, ot: OffloadedTable) -> None:
        with self._mu:
            lst = self._parked.get(op_id)
            if lst is not None:
                self._parked[op_id] = [e for e in lst if e[0] is not ot]

    # -- pressure -----------------------------------------------------------

    def _in_use(self) -> int:
        s = self.pool.stats()
        return int(s.get("bytes_in_use", 0)) - int(s.get("bytes_spilled",
                                                         0))

    def ensure_room(self, nbytes: int) -> None:
        """Spill largest-first until `nbytes` fits under the limit (best
        effort — stops when nothing spillable remains). Previously
        spilled entries remain candidates: a restore_slice() pin/unpin
        cycle brings a run's buffers back into memory, so the
        spilled-once flag is only a priority hint (fresh state first),
        not a permanent exclusion."""
        from bodo_tpu.utils import tracing
        while self._in_use() + nbytes > self.limit:
            with self._mu:
                entries = [(op, e) for op, lst in self._parked.items()
                           for e in lst]
            # fresh (never-spilled) victims first, then re-resident ones;
            # largest-first within each class
            entries.sort(key=lambda oe: (oe[1][2], -oe[1][1]))
            progress = False
            for op, e in entries:
                with tracing.event("comptroller_spill",
                                   operator=self._ops.get(op, "?"),
                                   bytes=e[1]):
                    spilled = e[0].spill()
                e[2] = True
                if spilled:
                    progress = True
                    self.n_spills += 1
                    self.bytes_spilled += e[1]
                    log(1, f"comptroller: spilled {e[1]} bytes of "
                           f"{self._ops.get(op, '?')} ({spilled} buffers)")
                    break
            if not progress:
                return

    def stats(self) -> dict:
        with self._mu:
            per_op = {self._ops[op]: sum(e[1] for e in lst)
                      for op, lst in self._parked.items()
                      if op in self._ops}
        return {"n_spills": self.n_spills,
                "bytes_spilled": self.bytes_spilled,
                "parked_bytes": per_op,
                "pool": self.pool.stats()}


_default_comptroller: Optional[OperatorComptroller] = None
_dc_lock = threading.Lock()


def default_comptroller() -> OperatorComptroller:
    global _default_comptroller
    with _dc_lock:
        if _default_comptroller is None:
            _default_comptroller = OperatorComptroller()
        return _default_comptroller


def set_default_comptroller(c: Optional[OperatorComptroller]) -> None:
    global _default_comptroller
    with _dc_lock:
        _default_comptroller = c
