"""Semantic result cache + incremental append maintenance.

Replaces the ad-hoc session dict in plan/physical.py (which keyed on the
raw structural ``node.key()`` — no dataset signature, so an overwritten
parquet file kept serving the stale result, and evicted in insertion
order regardless of how hot an entry was). The cache here keys every
entry on

    (plan fingerprint, environment key, dataset-signature digest)

where the fingerprint is the sha256 of the structural plan key, the
environment key pins the execution geometry (mesh width, shard policy,
precision mode) so mode sweeps never cross-serve, and the signature
digest covers the per-file (path, mtime, size) signatures of every
source the plan reads. A file overwrite changes the digest → natural
invalidation; an identical re-read hits.

Two entry tiers share one store:

  * node entries ("n", …) — per-plan-node memoization across queries,
    the successor of the old session dict;
  * query entries ("q", …) — whole-query results recorded at the
    execute() boundary, carrying everything incremental maintenance
    needs (the rebuildable plan template, per-source signatures, hidden
    aggregation partials).

INCREMENTAL MAINTENANCE: when a parquet dataset's signature changes by
*appended files only* (old signatures byte-identical, new files added —
``io.parquet.classify_change``), and the cached plan is a
concat-safe tree (ReadParquet/Filter/Projection/Union) optionally under
one terminal Aggregate/Reduce whose ops are distributive or algebraic
(sum/count/min/max, mean via hidden sum+count partials), the delta files
are scanned with a rebuilt template plan and spliced into the cached
result through the engine's own kernels:

    concat   : cached ++ delta                     (tail-append only)
    agg      : groupby(concat(cached, delta)) with sum→sum, count→sum,
               min→min, max→max; mean re-finalized from hidden partials
    reduce   : reduce(concat(cached_row, delta_row)), same merge ops

Any non-append change, non-incrementalizable plan, or mid-splice failure
invalidates cleanly to a full run — never a spliced partial.

MEMORY: cached results are device memory the governor must account for.
The cache holds one persistent "result_cache" grant resized to its
device footprint; admission rejects entries larger than the budget;
eviction is by benefit score (saved_wall × hit recency — an entry that
keeps getting hit and saved real wall survives pressure). Query entries
evicted under pressure spill to a host pandas tier (rehydrated — and
re-sharded — on the next hit); ``shed_for_pressure()`` lets the
governor's OOM handler drop the whole device tier rather than OOM a
query to keep a cache entry.

MULTI-TENANCY: every entry is tagged with the serving session that
recorded it (runtime/scheduler.py's contextvar; "-" outside the serving
layer) and per-session device bytes are accounted. Under device
pressure eviction is FAIR-SHARE: with more than one session holding
device entries, victims come from sessions above their equal share of
the budget (lowest benefit score first); when the inserting session is
the only one over its share, its own entry is the victim — a tenant
flooding the cache self-limits to its share and cannot evict another
tenant's within-share working set. ``stats()["by_session"]`` exposes
per-session hit/miss/eviction/byte counters (the isolation assertion in
``bench.py --suite serve`` reads these).

OWNERSHIP: the cache is PER-GANG — ownership is the (pid, gang_id)
pair. Device buffers in entries are only valid on the process that
created them, and the byte accounting assumes one governor. ``cache()``
asserts this: a plain fork (different pid, same gang identity) gets a
loud warning and a fresh empty cache instead of silently serving
another process's device handles, while a legitimate fleet gang
process (its own ``BODO_TPU_GANG_ID``) starts its private cache
silently. Cross-gang sharing happens explicitly through the fleet
peering tier (``set_peer_hooks`` / ``peer_export`` /
``invalidate_paths`` — runtime/fleet.py): on a local miss the owning
gang may import a peer's entry via the host pandas exchange format,
and a dataset mutation on any gang broadcasts the mutated source
paths so no peer ever serves a pre-mutation result.

Everything is best-effort: a cache failure must cost a recompute, never
the query.
"""

from __future__ import annotations

import contextlib
import hashlib
import os as _os
import threading
import time
import warnings
from typing import Dict, Optional, Set, Tuple

from bodo_tpu.config import config
from bodo_tpu.utils.logging import log

_HIDDEN_SUM = "__rc_s__"   # hidden mean partials: sum / count per out col
_HIDDEN_CNT = "__rc_c__"
_INCR_AGG_OPS = {"sum", "count", "min", "max", "mean"}
_MERGE_OP = {"sum": "sum", "count": "sum", "min": "min", "max": "max"}
_MAX_ENTRIES = 512         # entry-count backstop on top of the byte budget
_PIN_TIER = 1e9            # score floor per live view dependent (_score)
_AUTO_FRACTION = 0.125     # auto byte budget: slice of the derived budget
_AUTO_FLOOR = 64 << 20
_AUTO_DEFAULT = 256 << 20  # when no governor budget can be derived


# --------------------------------------------------------------------------
# keying: plan fingerprint + source signatures + environment
# --------------------------------------------------------------------------

_epoch = threading.local()


@contextlib.contextmanager
def signature_epoch():
    """One stat() per source per execute: signatures computed inside the
    epoch are snapshotted, so the per-node lookups of a single execute
    all see (and pay for) one consistent view of the filesystem."""
    depth = getattr(_epoch, "depth", 0)
    if depth == 0:
        _epoch.sigs = {}
    _epoch.depth = depth + 1
    try:
        yield
    finally:
        _epoch.depth -= 1
        if _epoch.depth == 0:
            _epoch.sigs = None


def _sources_of(node):
    """Structural source list of a subplan: tuple of ("pq", path) /
    ("csv", path) / ("mem", id), or None when the plan reads something
    the cache cannot sign. Memoized on the node (structure is
    immutable)."""
    s = getattr(node, "_rc_srcs", False)
    if s is not False:
        return s
    from bodo_tpu.plan import logical as L
    if not node.children:
        if isinstance(node, L.ReadParquet):
            s = (("pq", node.path),)
        elif isinstance(node, L.ReadCsv):
            s = (("csv", node.path),)
        elif isinstance(node, L.FromPandas):
            s = (("mem", node._id),)
        elif isinstance(node, L.ViewScan):
            # a view scan signs as its view's BASE sources (resolved
            # transitively through the view DAG): a consumer's key then
            # rolls over exactly when the underlying data changes, even
            # though the consumer reads the cached materialization
            import sys
            vw = sys.modules.get("bodo_tpu.runtime.views")
            s = vw.base_sources(node.name) if vw is not None else None
        else:
            s = None
    else:
        acc = []
        s = ()
        for c in node.children:
            cs = _sources_of(c)
            if cs is None:
                s = None
                break
            acc.extend(cs)
        if s is not None:
            seen: Set = set()
            out = []
            for x in acc:
                if x not in seen:
                    seen.add(x)
                    out.append(x)
            s = tuple(out)
    node._rc_srcs = s
    return s


def _source_sig(kind: str, ident):
    """Content signature for one source, or None (uncacheable). Failures
    are loud-once via the stats store's degraded-signature channel —
    a signature that silently collapses would alias two datasets."""
    cache_d = getattr(_epoch, "sigs", None)
    k = (kind, ident)
    if cache_d is not None and k in cache_d:
        return cache_d[k]
    try:
        if kind == "pq":
            from bodo_tpu.io.parquet import dataset_signature
            sig = dataset_signature(ident)
        elif kind == "csv":
            import os
            st = os.stat(ident)
            sig = ((str(ident), st.st_mtime_ns, st.st_size),)
        else:  # "mem": identity lives in the fingerprint's counter id
            sig = ()
    except Exception as e:  # noqa: BLE001 - uncacheable, not fatal
        from bodo_tpu.runtime import stats_store
        stats_store.note_signature_failure(ident, e)
        sig = None
    if cache_d is not None:
        cache_d[k] = sig
    return sig


def _plan_fp(node) -> str:
    fp = getattr(node, "_rc_fp", None)
    if fp is None:
        fp = hashlib.sha256(repr(node.key()).encode()).hexdigest()[:24]
        node._rc_fp = fp
    return fp


def _env_key() -> tuple:
    """Execution geometry baked into every key: a result computed on one
    mesh/shard policy must not serve a query running under another."""
    from bodo_tpu.parallel import mesh as mesh_mod
    return (mesh_mod.num_shards(), int(config.shard_min_rows),
            bool(getattr(config, "low_precision_agg", False)))


def _sig_digest(sigs) -> str:
    return hashlib.sha256(repr(sigs).encode()).hexdigest()[:24]


class _QueryInfo:
    __slots__ = ("fp", "env", "sigs", "key", "raw")

    def __init__(self, fp, env, sigs, key, raw):
        self.fp, self.env, self.sigs, self.key, self.raw = \
            fp, env, sigs, key, raw


# --------------------------------------------------------------------------
# incremental-maintenance plan analysis
# --------------------------------------------------------------------------

def _concat_safe(node) -> bool:
    """True when executing the plan over D++Δ equals (plan over D) ++
    (plan over Δ) as a row multiset: per-row operators over scans."""
    from bodo_tpu.plan import logical as L
    if isinstance(node, L.ReadParquet):
        return True
    if isinstance(node, (L.Filter, L.Projection)):
        return _concat_safe(node.child)
    if isinstance(node, L.Union):
        return all(_concat_safe(c) for c in node.children)
    return False


def _parquet_scans(node, out=None):
    from bodo_tpu.plan import logical as L
    if out is None:
        out = []
    if isinstance(node, L.ReadParquet):
        out.append(node)
    for c in node.children:
        _parquet_scans(c, out)
    return out


def _rebuild(node, scan_files=None):
    """Fresh structural clone of an incrementally-maintainable plan (no
    memoized ``_cached`` tables pinned); ``scan_files`` swaps every
    parquet scan's file list — that is the delta plan."""
    from bodo_tpu.plan import logical as L
    if isinstance(node, L.ReadParquet):
        path = node.path if scan_files is None else tuple(scan_files)
        return L.ReadParquet(path, columns=list(node.columns))
    if isinstance(node, L.Filter):
        return L.Filter(_rebuild(node.child, scan_files), node.predicate)
    if isinstance(node, L.Projection):
        return L.Projection(_rebuild(node.child, scan_files), node.exprs)
    if isinstance(node, L.Union):
        return L.Union([_rebuild(c, scan_files) for c in node.children])
    if isinstance(node, L.Aggregate):
        return L.Aggregate(_rebuild(node.child, scan_files), node.keys,
                           node.aggs)
    if isinstance(node, L.Reduce):
        return L.Reduce(_rebuild(node.child, scan_files), node.aggs)
    raise TypeError(f"not incrementally maintainable: "
                    f"{type(node).__name__}")


def _analyze_incremental(root) -> Optional[dict]:
    """Decide whether a plan supports append splicing; when it does,
    return the execution recipe: possibly-augmented exec root (hidden
    sum/count partials for mean re-finalize), the visible column list,
    and a rebuildable template. None → plain full runs only."""
    from bodo_tpu.plan import logical as L
    from bodo_tpu.table import dtypes as dt
    shape = None
    if isinstance(root, (L.Aggregate, L.Reduce)):
        child = root.child
        aggs = root.aggs
        if not _concat_safe(child) or not aggs:
            return None
        for col, op, _out in aggs:
            if op not in _INCR_AGG_OPS:
                return None
            if op == "mean" and not dt.is_numeric(child.schema[col]):
                return None
        shape = "agg" if isinstance(root, L.Aggregate) else "reduce"
    elif _concat_safe(root):
        shape = "concat"
        child = root
    else:
        return None
    scans = _parquet_scans(root)
    if not scans or len({s.path for s in scans}) != 1:
        return None  # exactly one dataset: the delta plan swaps its files
    if shape == "concat" and len(scans) > 1:
        return None  # multi-scan concat would reorder rows on splice
    path = scans[0].path
    import os
    if not os.path.isdir(path):
        # a single-file scan cannot grow by appended files — any change
        # is a mutation, so augmenting (and recompiling) for a future
        # splice would be pure overhead on the hot single-file path
        return None
    keys = list(getattr(root, "keys", []))
    means = []
    exec_root, visible = root, None
    if shape in ("agg", "reduce"):
        exec_aggs = list(aggs)
        taken = set(child.schema) | set(keys) | {o for _c, _o2, o in aggs}
        for col, op, out in aggs:
            if op != "mean":
                continue
            s_name, c_name = _HIDDEN_SUM + out, _HIDDEN_CNT + out
            if s_name in taken or c_name in taken:
                return None  # hidden-name collision: bail out entirely
            taken |= {s_name, c_name}
            exec_aggs.append((col, "sum", s_name))
            exec_aggs.append((col, "count", c_name))
            means.append((out, s_name, c_name))
        if means:
            exec_root = (L.Aggregate(child, keys, exec_aggs)
                         if shape == "agg" else L.Reduce(child, exec_aggs))
            visible = list(root.schema)
        aggs = exec_aggs
    else:
        aggs = []
    return {"shape": shape, "keys": keys, "aggs": aggs, "means": means,
            "order": list(exec_root.schema), "path": path,
            "exec_root": exec_root, "visible": visible,
            "template": _rebuild(exec_root)}


def _refinalize_means(merged, incr, proto):
    """mean = hidden_sum / hidden_count, mirroring the groupby kernel's
    finalize (s / max(cnt, 1), NaN where the group is empty) in the
    result dtype the original plan produced."""
    import jax.numpy as jnp

    from bodo_tpu.table.table import Column
    cols = dict(merged.columns)
    for out, s_name, c_name in incr["means"]:
        rdt = proto.columns[out].dtype
        sv = cols[s_name].data.astype(rdt.numpy)
        cv = cols[c_name].data
        m = sv / jnp.maximum(cv, 1)
        m = jnp.where(cv > 0, m, jnp.nan).astype(rdt.numpy)
        cols[out] = Column(m, None, rdt)
    return merged.with_columns(cols)


def _splice(old_t, delta_t, incr):
    """Merge a delta-plan result into the cached result through the
    engine's own kernels — same code paths, same dtypes, same
    distribution policy as a full run."""
    from bodo_tpu import relational as R
    if list(delta_t.names) != list(old_t.names):
        delta_t = delta_t.select(old_t.names)
    shape = incr["shape"]
    if shape == "concat":
        from bodo_tpu.plan import physical
        return physical._maybe_shard(R.concat_tables([old_t, delta_t]))
    merge = [(out, _MERGE_OP[op], out)
             for _c, op, out in incr["aggs"] if op != "mean"]
    both = R.concat_tables([old_t, delta_t])
    if shape == "agg":
        merged = R.groupby_agg(both, incr["keys"], merge)
        if incr["means"]:
            merged = _refinalize_means(merged, incr, old_t)
        return merged.select(incr["order"])
    # reduce: merge the two 1-row partial tables, re-finalize means the
    # same way reduce_table's host finalize does (sum / count, NaN empty)
    import pandas as pd

    from bodo_tpu.table.table import Table
    scalars = R.reduce_table(both, merge)
    for out, s_name, c_name in incr["means"]:
        cnt = int(scalars[c_name])
        scalars[out] = float(scalars[s_name]) / cnt if cnt \
            else float("nan")
    df = pd.DataFrame({k: [scalars[k]] for k in incr["order"]})
    return Table.from_pandas(df)


def _classify_append(old_sigs, new_sigs):
    """(delta_files, tail_only) when every source change is append-only;
    None on any mutate/mixed change. ``tail_only`` is True when the
    delta files strictly follow the old files in scan order — required
    for concat-shape splices, which must preserve row order."""
    if len(old_sigs) != len(new_sigs):
        return None
    from bodo_tpu.io.parquet import classify_change
    delta = []
    tail_only = True
    changed = False
    for (ok_, oid, osig), (nk, nid, nsig) in zip(old_sigs, new_sigs):
        if ok_ != nk or oid != nid:
            return None
        if osig == nsig:
            continue
        if ok_ != "pq":
            return None
        verdict, files = classify_change(osig, nsig)
        if verdict != "append":
            return None
        changed = True
        delta.extend(files)
        if tuple(nsig[:len(osig)]) != tuple(osig):
            # an in-place grown file keeps its old rows where they were;
            # the growth is tail-ordered only when the grown file is the
            # LAST old file in scan order (its new row groups then follow
            # every cached row, so a concat splice stays row-ordered)
            grown = {str(f).rpartition("#rg=")[0] for f in files
                     if "#rg=" in str(f)}
            prefix_ok = all(a == b for a, b in zip(osig[:-1], nsig)) \
                if osig else False
            last_o = osig[-1] if osig else None
            last_n = nsig[len(osig) - 1] if osig else None
            if not (prefix_ok and last_o[0] == last_n[0]
                    and last_o[0] in grown):
                tail_only = False
    if not changed or not delta:
        return None
    return tuple(delta), tail_only


# --------------------------------------------------------------------------
# the cache
# --------------------------------------------------------------------------

def _gang_id() -> str:
    """This process's fleet gang identity ("" outside fleet mode). Read
    from the environment, not config — ownership checks must agree with
    what the fleet controller exported at spawn time."""
    return _os.environ.get("BODO_TPU_GANG_ID", "")


def _current_session() -> str:
    """Serving-session label for attribution ("-" outside the serving
    layer). Read via sys.modules.get — recording a cache entry must
    never import the scheduler."""
    import sys
    sch = sys.modules.get("bodo_tpu.runtime.scheduler")
    if sch is None:
        return "-"
    try:
        return sch.current_session() or "-"
    except Exception:  # noqa: BLE001 - attribution is best-effort
        return "-"


class _Entry:
    __slots__ = ("key", "raw", "kind", "table", "host", "dist", "nbytes",
                 "host_nbytes", "saved_wall_s", "hits", "last_use",
                 "sources", "visible", "incr", "session", "parts",
                 "parts_nbytes")

    def __init__(self, key, raw, kind):
        self.key, self.raw, self.kind = key, raw, kind
        self.table = None
        self.host = None
        self.dist = None
        self.nbytes = 0
        self.host_nbytes = 0
        self.saved_wall_s = 0.0
        self.hits = 0
        self.last_use = 0.0
        self.sources = None
        self.visible = None
        self.incr = None
        self.session = "-"
        # partition-level invalidation: per-source-file host partials of
        # the exec-root output ({file path -> pandas}), so a mutate of
        # ONE file re-runs one delta plan and re-merges instead of
        # nuking the whole entry (see _try_partition_refresh)
        self.parts = None
        self.parts_nbytes = 0


class ResultCache:
    """Two-tier (device Table / host pandas) semantic result store with
    benefit-scored eviction and governor-charged admission."""

    def __init__(self):
        self._mu = threading.RLock()
        self._entries: Dict[tuple, _Entry] = {}
        self._by_fp: Dict[tuple, tuple] = {}    # (fp, env) -> query key
        self._by_raw: Dict[tuple, Set[tuple]] = {}
        self._refs: Dict[int, list] = {}        # id(table) -> [refs, bytes]
        self.device_bytes = 0
        self.host_bytes = 0
        self.saved_wall_s = 0.0
        self._grant = None
        self._grant_bytes = 0
        self._budget_cache: Optional[int] = None
        self._budget_at = 0.0
        self._c: Dict[str, int] = {}
        self._sess: Dict[str, Dict[str, int]] = {}  # session -> counters
        # plan fingerprint -> live dependent count (downstream views +
        # subscribers); weights eviction benefit so a view DAG root is
        # not evicted under its own fan-out (runtime/views.py maintains)
        self._view_pins: Dict[str, int] = {}
        self._owner_pid = _os.getpid()
        self._owner_gang = _gang_id()

    # -- plumbing ------------------------------------------------------------

    def _now(self) -> float:
        return time.monotonic()

    def count(self, name: str, n: int = 1) -> None:
        with self._mu:
            self._c[name] = self._c.get(name, 0) + n

    def _count_sess_locked(self, session: str, name: str,
                           n: int = 1) -> None:
        d = self._sess.setdefault(session or "-", {})
        d[name] = d.get(name, 0) + n

    def assert_single_gang_owner(self) -> None:
        """Hard ownership check: this cache's device buffers belong to
        the (pid, gang_id) that created them."""
        if (self._owner_pid, self._owner_gang) != \
                (_os.getpid(), _gang_id()):
            raise AssertionError(
                f"result cache owned by pid={self._owner_pid} "
                f"gang={self._owner_gang or '-'} used from "
                f"pid={_os.getpid()} gang={_gang_id() or '-'}: device "
                f"entries are per-gang; fleet gangs each own a private "
                f"cache (BODO_TPU_GANG_ID) and exchange results via "
                f"the peering tier (runtime/fleet.py)")

    def _device_budget(self) -> int:
        b = int(config.result_cache_bytes)
        if b > 0:
            return b
        # auto mode re-probes the governor's derived budget at most
        # once a second: this sits on the per-node record path
        now = self._now()
        if self._budget_cache is not None \
                and now - self._budget_at < 1.0:
            return self._budget_cache
        try:
            from bodo_tpu.runtime.memory_governor import governor
            derived = governor().derived_budget()
        except Exception:  # noqa: BLE001
            derived = 0
        out = max(_AUTO_FLOOR, int(derived * _AUTO_FRACTION)) \
            if derived else _AUTO_DEFAULT
        self._budget_cache, self._budget_at = out, now
        return out

    def _score(self, e: _Entry) -> float:
        """Benefit = saved wall × hit recency: evicting min keeps the
        entries that keep earning their memory. A view materialization
        serving N live dependents (downstream views + subscribers) is
        guaranteed future reuse on a schedule LRU cannot see (the next
        maintenance pass, not the next user query), so pinned entries
        rank a whole tier above every unpinned candidate — saved wall
        can be milliseconds on a warm gang and no multiplier of it
        reliably beats a freshly-recorded scan. Within the pinned
        tier, more dependents and saved wall still order victims; the
        eviction loop can still reclaim pinned entries once they are
        the only candidates left, so the budget always wins."""
        if e.kind == "q" and self._view_pins:
            deps = self._view_pins.get(e.key[1], 0)
            if deps:
                return _PIN_TIER * deps + e.saved_wall_s * (1.0 + e.hits)
        age = max(self._now() - e.last_use, 0.0)
        return (e.saved_wall_s * (1.0 + e.hits)) / (age + 1.0)

    def set_view_pin(self, fp: str, deps: int) -> None:
        """Declare fp's live dependent count (0 clears the pin)."""
        with self._mu:
            if deps > 0:
                self._view_pins[fp] = int(deps)
            else:
                self._view_pins.pop(fp, None)

    def clear_view_pins(self) -> None:
        with self._mu:
            self._view_pins.clear()

    def _sync_grant_locked(self) -> None:
        """Keep one persistent governor grant sized to the device
        footprint, so cached results are visible memory pressure.
        Resyncs are throttled to >=1 MiB drift: the grant is advisory
        accounting and this sits on the per-node record path."""
        if not config.mem_governor:
            return
        if self._grant is not None and self.device_bytes > 0 and \
                abs(self.device_bytes - self._grant_bytes) < (1 << 20):
            return
        try:
            from bodo_tpu.runtime import memory_governor as mg
            if self.device_bytes <= 0:
                if self._grant is not None:
                    g, self._grant = self._grant, None
                    self._grant_bytes = 0
                    g.release()
                return
            gov = mg.governor()
            if self._grant is None:
                self._grant = gov.admit("result_cache",
                                        want=self.device_bytes,
                                        wait=False)
            gov.resize_grant(self._grant, self.device_bytes)
            self._grant_bytes = self.device_bytes
        except Exception:  # noqa: BLE001 - accounting is best-effort
            pass

    def _charge_locked(self, e: _Entry, table, nbytes: int) -> None:
        r = self._refs.get(id(table))
        if r is None:
            self._refs[id(table)] = [1, nbytes]
            self.device_bytes += nbytes
        else:
            r[0] += 1
        e.table = table
        e.nbytes = nbytes

    def _deref_locked(self, e: _Entry) -> None:
        t = e.table
        if t is None:
            return
        e.table = None
        r = self._refs.get(id(t))
        if r is not None:
            r[0] -= 1
            if r[0] <= 0:
                self.device_bytes -= r[1]
                del self._refs[id(t)]

    def _drop_locked(self, e: _Entry) -> None:
        self._deref_locked(e)
        if e.host is not None:
            self.host_bytes -= e.host_nbytes
            e.host, e.host_nbytes = None, 0
        if e.parts is not None:
            self.host_bytes -= e.parts_nbytes
            e.parts, e.parts_nbytes = None, 0
        self._entries.pop(e.key, None)
        ks = self._by_raw.get(e.raw)
        if ks is not None:
            ks.discard(e.key)
            if not ks:
                del self._by_raw[e.raw]
        if e.kind == "q":
            fpk = (e.key[1], e.key[2])
            if self._by_fp.get(fpk) == e.key:
                del self._by_fp[fpk]

    def _spill_locked(self, e: _Entry) -> None:
        """Device → host pandas tier (query entries only — node-level
        memoization is not worth a host copy)."""
        if e.kind != "q" or not config.result_cache_host_spill \
                or int(config.result_cache_host_bytes) <= 0:
            self._drop_locked(e)
            return
        try:
            df = e.table.to_pandas()
            nb = int(df.memory_usage(deep=True).sum())
        except Exception:  # noqa: BLE001
            self._drop_locked(e)
            return
        self._deref_locked(e)
        e.host = df
        e.host_nbytes = nb
        self.host_bytes += nb
        self._c["spills"] = self._c.get("spills", 0) + 1

    def _rehydrate_locked(self, e: _Entry):
        """Host → device on a hit, restoring the original distribution
        (a 1D result re-shards over the current mesh)."""
        from bodo_tpu.parallel import mesh as mesh_mod
        from bodo_tpu.runtime.memory_governor import table_device_bytes
        from bodo_tpu.table.table import ONED, Table
        t = Table.from_pandas(e.host)
        if e.dist == ONED and mesh_mod.num_shards() > 1:
            t = t.shard()
        nb = int(table_device_bytes(t))
        self.host_bytes -= e.host_nbytes
        e.host, e.host_nbytes = None, 0
        self._charge_locked(e, t, nb)
        self._c["rehydrations"] = self._c.get("rehydrations", 0) + 1
        self._evict_locked(keep=e.key)
        self._sync_grant_locked()
        return t

    def _sess_dev_locked(self) -> Dict[str, int]:
        """Per-session device bytes (entry-attributed: a table shared
        across sessions counts toward each holder's footprint, which is
        the conservative side for fair-share comparisons)."""
        by: Dict[str, int] = {}
        for e in self._entries.values():
            if e.table is not None:
                by[e.session] = by.get(e.session, 0) + e.nbytes
        return by

    def _device_victim_locked(self, budget: int, keep) -> Optional[_Entry]:
        """Fair-share victim choice. Single tenant: global min benefit
        score (original behavior). Multiple tenants: victims come from
        sessions above their equal share of the budget; when only the
        inserting (keep) session is over its share, ITS entry is the
        victim — a flooding tenant self-limits instead of evicting a
        within-share working set of another tenant."""
        cands = [e for e in self._entries.values()
                 if e.table is not None and e.key != keep]
        by_sess = self._sess_dev_locked()
        if len(by_sess) > 1:
            share = budget // len(by_sess)
            over = [e for e in cands if by_sess.get(e.session, 0) > share]
            if over:
                return min(over, key=self._score)
            keep_e = self._entries.get(keep) if keep is not None else None
            if keep_e is not None and keep_e.table is not None \
                    and by_sess.get(keep_e.session, 0) > share:
                return keep_e
        if not cands:
            cands = [e for e in self._entries.values()
                     if e.table is not None]
        return min(cands, key=self._score) if cands else None

    def _evict_locked(self, keep=None) -> None:
        budget = self._device_budget()
        while self.device_bytes > budget:
            victim = self._device_victim_locked(budget, keep)
            if victim is None:
                break
            self._c["evictions"] = self._c.get("evictions", 0) + 1
            self._count_sess_locked(victim.session, "evicted")
            self._spill_locked(victim)
        host_budget = max(int(config.result_cache_host_bytes), 0)
        while self.host_bytes > host_budget:
            cands = [e for e in self._entries.values()
                     if e.host is not None]
            if not cands:
                break
            self._drop_locked(min(cands, key=self._score))
        while len(self._entries) > _MAX_ENTRIES:
            cands = [e for e in self._entries.values() if e.key != keep]
            if not cands:
                break
            victim = min(cands, key=self._score)
            self._c["evictions"] = self._c.get("evictions", 0) + 1
            self._count_sess_locked(victim.session, "evicted")
            self._drop_locked(victim)

    # -- store/lookup --------------------------------------------------------

    def record(self, key, raw, table, wall_s, *, kind="n", sources=None,
               visible=None, incr=None) -> None:
        if key is None or not config.result_cache:
            return
        try:
            from bodo_tpu.runtime.memory_governor import \
                table_device_bytes
            nbytes = int(table_device_bytes(table))
        except Exception:  # noqa: BLE001
            nbytes = 0
        session = _current_session()
        with self._mu:
            if nbytes > self._device_budget():
                self._c["rejected"] = self._c.get("rejected", 0) + 1
                self._count_sess_locked(session, "rejected")
                return
            old = self._entries.get(key)
            if old is not None:
                self._drop_locked(old)
            e = _Entry(key, raw, kind)
            e.saved_wall_s = max(float(wall_s), 0.0)
            e.last_use = self._now()
            e.dist = table.distribution
            e.sources = sources
            e.visible = visible
            e.incr = incr
            e.session = session
            self._count_sess_locked(session, "records")
            self._entries[key] = e
            self._charge_locked(e, table, nbytes)
            self._by_raw.setdefault(raw, set()).add(key)
            if kind == "q":
                self._by_fp[(key[1], key[2])] = key
            self._evict_locked(keep=key)
            self._sync_grant_locked()

    def lookup(self, key, *, prefix: str = ""):
        """Table for a key, counting {prefix}hits/{prefix}misses; host
        entries rehydrate transparently."""
        if key is None or not config.result_cache:
            return None
        session = _current_session()
        with self._mu:
            e = self._entries.get(key)
            if e is None:
                self._c[prefix + "misses"] = \
                    self._c.get(prefix + "misses", 0) + 1
                self._count_sess_locked(session, prefix + "misses")
                return None
            e.hits += 1
            e.last_use = self._now()
            t = e.table
            if t is None:
                try:
                    t = self._rehydrate_locked(e)
                except Exception:  # noqa: BLE001
                    self._drop_locked(e)
                    self._c[prefix + "misses"] = \
                        self._c.get(prefix + "misses", 0) + 1
                    self._count_sess_locked(session, prefix + "misses")
                    return None
            self._c[prefix + "hits"] = self._c.get(prefix + "hits", 0) + 1
            self._count_sess_locked(session, prefix + "hits")
            self.saved_wall_s += e.saved_wall_s
            return t

    def attach_parts(self, key, parts) -> bool:
        """Attach (or replace) an entry's per-source-file contribution
        map; partials are host pandas, charged to the host tier."""
        try:
            nb = sum(int(df.memory_usage(deep=True).sum())
                     for df in parts.values())
        except Exception:  # noqa: BLE001
            return False
        with self._mu:
            e = self._entries.get(key)
            if e is None:
                return False
            if e.parts is not None:
                self.host_bytes -= e.parts_nbytes
            e.parts = dict(parts)
            e.parts_nbytes = nb
            self.host_bytes += nb
            return True

    def build_parts(self, key, run, max_parts: Optional[int] = None) \
            -> bool:
        """Build the contribution map for an incrementalizable cached
        entry: one delta plan per source file, partials in NEW-scan-order
        merge form. Skipped (False) past ``max_parts`` files — the map
        costs one pass over the dataset, paid once per materialization."""
        with self._mu:
            e = self._entries.get(key)
            if e is None or e.incr is None or not e.sources:
                return False
            if len(e.sources) != 1 or e.sources[0][0] != "pq":
                return False
            files = [s[0] for s in e.sources[0][2]]
            incr = e.incr
        if not files or (max_parts is not None
                         and len(files) > max_parts):
            return False
        parts = {}
        try:
            for f in files:
                droot = _rebuild(incr["template"], scan_files=(f,))
                parts[f] = run(droot).to_pandas()
        except Exception:  # noqa: BLE001 - the map is an optimization
            return False
        self.count("parts_built", len(files))
        return self.attach_parts(key, parts)

    def _merge_parts(self, parts, order, incr):
        """Merge per-file partials (NEW scan order) through the same
        kernels a splice uses — same dtypes, same distribution policy."""
        import pandas as pd

        from bodo_tpu import relational as R
        from bodo_tpu.table.table import Table
        df = pd.concat([parts[f] for f in order], ignore_index=True)
        t = Table.from_pandas(df)
        shape = incr["shape"]
        if shape == "concat":
            from bodo_tpu.plan import physical
            return physical._maybe_shard(t)
        merge = [(out, _MERGE_OP[op], out)
                 for _c, op, out in incr["aggs"] if op != "mean"]
        if shape == "agg":
            merged = R.groupby_agg(t, incr["keys"], merge)
            if incr["means"]:
                merged = _refinalize_means(merged, incr, t)
            return merged.select(incr["order"])
        scalars = R.reduce_table(t, merge)
        for out, s_name, c_name in incr["means"]:
            cnt = int(scalars[c_name])
            scalars[out] = float(scalars[s_name]) / cnt if cnt \
                else float("nan")
        df2 = pd.DataFrame({k: [scalars[k]] for k in incr["order"]})
        return Table.from_pandas(df2)

    def _try_partition_refresh(self, root, prev, qi, run):
        """Partition-level invalidation: when the superseded entry
        carries a contribution map and the change mutated/added SOME
        files in place (no deletions), re-run delta plans for only those
        files and re-merge — unaffected partitions re-serve their cached
        partials without recompute. Any ambiguity (deleted file, partial
        missing from the map, merge failure) returns None and the caller
        falls back to full invalidation — never a stale partial."""
        if prev.incr is None or not prev.sources or prev.parts is None:
            return None
        if len(prev.sources) != 1 or len(qi.sigs) != 1:
            return None
        (ok_, oid, osig), (nk, nid, nsig) = prev.sources[0], qi.sigs[0]
        if ok_ != "pq" or nk != "pq" or oid != nid:
            return None
        old_by = {s[0]: s for s in osig}
        new_by = {s[0]: s for s in nsig}
        if any(p not in new_by for p in old_by):
            return None  # deletion: no partial split can be trusted
        changed = [s[0] for s in nsig
                   if s[0] in old_by and old_by[s[0]] != s]
        added = [s[0] for s in nsig if s[0] not in old_by]
        if not changed and not added:
            return None
        if any(p not in prev.parts for p in changed):
            return None
        t0 = time.perf_counter()
        try:
            parts = dict(prev.parts)
            for f in changed + added:
                droot = _rebuild(prev.incr["template"], scan_files=(f,))
                droot._explain_path = getattr(root, "_explain_path",
                                              None)
                parts[f] = run(droot).to_pandas()
            order = [s[0] for s in nsig]
            merged = self._merge_parts(parts, order, prev.incr)
        except Exception as e:  # noqa: BLE001 - never a stale partial
            self.count("incremental_fallbacks")
            log(1, f"result cache: partition refresh failed "
                   f"({type(e).__name__}: {e}); falling back to full "
                   f"invalidation")
            return None
        wall = time.perf_counter() - t0
        self.count("partition_refresh")
        self.count("parts_reused",
                   len(order) - len(changed) - len(added))
        self.record(qi.key, qi.raw, merged, prev.saved_wall_s, kind="q",
                    sources=qi.sigs, visible=prev.visible,
                    incr=prev.incr)
        self.attach_parts(qi.key, parts)
        with self._mu:
            if self._entries.get(prev.key) is prev:
                self._drop_locked(prev)
            self._sync_grant_locked()
        log(1, f"result cache: partition refresh over "
               f"{len(changed) + len(added)} of {len(order)} file(s) "
               f"in {wall:.3f}s")
        _explain_rcache(root, merged,
                        {"event": "partition_refresh",
                         "changed_files": len(changed) + len(added),
                         "wall_s": round(wall, 6)})
        vis = prev.visible
        return merged.select(vis) if vis else merged

    def _materialize(self, e: _Entry):
        """Device table for an entry the caller already holds (no hit
        accounting) — None when it vanished or cannot rehydrate."""
        with self._mu:
            if self._entries.get(e.key) is not e:
                return None
            e.last_use = self._now()
            if e.table is not None:
                return e.table
            try:
                return self._rehydrate_locked(e)
            except Exception:  # noqa: BLE001
                self._drop_locked(e)
                return None

    # -- query boundary ------------------------------------------------------

    def _query_info(self, root) -> Optional[_QueryInfo]:
        if not config.result_cache:
            return None
        srcs = _sources_of(root)
        if srcs is None:
            return None
        sigs = []
        for kind, ident in srcs:
            s = _source_sig(kind, ident)
            if s is None:
                self.count("sig_uncacheable")
                return None
            sigs.append((kind, ident, s))
        sigs = tuple(sigs)
        fp = _plan_fp(root)
        env = _env_key()
        key = ("q", fp, env, _sig_digest(sigs))
        return _QueryInfo(fp, env, sigs, key, root.key())

    def cached_execute(self, root, run):
        """The execute() boundary: exact hit → serve; append-only change
        on an incrementalizable cached plan → delta scan + splice; any
        other change → invalidate + full run; miss → timed full run,
        recorded (with hidden partials when the plan supports future
        splices)."""
        if not config.result_cache:
            return run(root)
        with signature_epoch():
            try:
                qi = self._query_info(root)
            except Exception:  # noqa: BLE001 - keying must never fail exec
                qi = None
            if qi is None:
                return run(root)
            with self._mu:
                e = self._entries.get(qi.key)
                saved = e.saved_wall_s if e is not None else 0.0
            t = self.lookup(qi.key, prefix="q_")
            if t is not None:
                vis = e.visible if e is not None else None
                _explain_rcache(root, t, {"event": "hit",
                                          "saved_s": round(saved, 6)})
                return t.select(vis) if vis else t
            with self._mu:
                pk = self._by_fp.get((qi.fp, qi.env))
                prev = self._entries.get(pk) if pk is not None else None
            if prev is not None and prev.key != qi.key:
                out = self._try_incremental(root, prev, qi, run)
                if out is None:
                    out = self._try_partition_refresh(root, prev, qi,
                                                      run)
                if out is not None:
                    return out
                # same plan over changed data and no clean splice: the
                # stale entry can never be served again — drop it, and
                # tell the fleet (when peered) so no other gang serves
                # its copy of the pre-mutation result
                with self._mu:
                    if self._entries.get(prev.key) is prev:
                        self._drop_locked(prev)
                        self._c["invalidations"] = \
                            self._c.get("invalidations", 0) + 1
                    self._sync_grant_locked()
                self._notify_invalidated(prev)
            t = self._peer_fill(root, qi)
            if t is not None:
                return t
            return self._full_run(root, qi, run)

    def _full_run(self, root, qi, run):
        try:
            incr = _analyze_incremental(root)
        except Exception:  # noqa: BLE001 - analysis must never fail exec
            incr = None
        exec_root = incr["exec_root"] if incr else root
        visible = incr["visible"] if incr else None
        if exec_root is not root:
            # augmented plan: inherit the root's EXPLAIN identity and
            # give it its own fusion annotations (best-effort)
            exec_root._explain_path = getattr(root, "_explain_path", None)
            try:
                from bodo_tpu.plan.fusion import plan_fusion_groups
                plan_fusion_groups(exec_root)
            except Exception:  # noqa: BLE001
                pass
        t0 = time.perf_counter()
        t = run(exec_root)
        wall = time.perf_counter() - t0
        entry_incr = None
        if incr:
            entry_incr = {k: incr[k] for k in
                          ("shape", "keys", "aggs", "means", "order",
                           "path", "template")}
        self.record(qi.key, qi.raw, t, wall, kind="q", sources=qi.sigs,
                    visible=visible, incr=entry_incr)
        return t.select(visible) if visible else t

    def _try_incremental(self, root, prev, qi, run):
        """Delta scan + splice against a superseded entry; None when the
        change is not append-only, the plan does not support it, or the
        splice fails (caller falls back to a clean full run)."""
        if prev.incr is None or prev.sources is None:
            return None
        try:
            appended = _classify_append(prev.sources, qi.sigs)
        except Exception:  # noqa: BLE001
            appended = None
        if appended is None:
            return None
        delta_files, tail_only = appended
        if prev.incr["shape"] == "concat" and not tail_only:
            return None
        t0 = time.perf_counter()
        try:
            old_t = self._materialize(prev)
            if old_t is None:
                return None
            delta_root = _rebuild(prev.incr["template"],
                                  scan_files=delta_files)
            delta_root._explain_path = getattr(root, "_explain_path",
                                               None)
            delta_t = run(delta_root)
            merged = _splice(old_t, delta_t, prev.incr)
        except Exception as e:  # noqa: BLE001 - never a spliced partial
            self.count("incremental_fallbacks")
            log(1, f"result cache: incremental refresh failed "
                   f"({type(e).__name__}: {e}); falling back to full "
                   f"run")
            return None
        wall = time.perf_counter() - t0
        self.count("q_incremental")
        # the refreshed entry inherits the superseded entry's benefit
        # estimate: serving it still saves a full recompute
        self.record(qi.key, qi.raw, merged, prev.saved_wall_s, kind="q",
                    sources=qi.sigs, visible=prev.visible,
                    incr=prev.incr)
        with self._mu:
            if self._entries.get(prev.key) is prev:
                self._drop_locked(prev)
            self._sync_grant_locked()
        log(1, f"result cache: incremental refresh over "
               f"{len(delta_files)} appended file(s) in {wall:.3f}s")
        _explain_rcache(root, merged,
                        {"event": "incremental",
                         "delta_files": len(delta_files),
                         "wall_s": round(wall, 6)})
        vis = prev.visible
        return merged.select(vis) if vis else merged

    # -- fleet peering -------------------------------------------------------

    def _peer_fill(self, root, qi):
        """On a local q-miss, ask the fleet peering tier (when hooked)
        for the fingerprint's previous owner's copy before recomputing.
        A successful import is recorded locally like a fresh result, so
        the NEXT repeat is a plain device hit."""
        fetch = _peer_fetch
        if fetch is None or not getattr(config, "fleet_peering", True):
            return None
        try:
            payload = fetch(qi.key)
        except Exception:  # noqa: BLE001 - peering is best-effort
            payload = None
        if not payload:
            self.count("peer_misses")
            return None
        try:
            from bodo_tpu.parallel import mesh as mesh_mod
            from bodo_tpu.table.table import Table
            t = Table.from_pandas(payload["df"])
            if payload.get("dist") == "1D" and mesh_mod.num_shards() > 1:
                t = t.shard()
        except Exception:  # noqa: BLE001 - a bad payload costs a rerun
            self.count("peer_misses")
            return None
        self.count("peer_hits")
        vis = payload.get("visible")
        self.record(qi.key, qi.raw, t,
                    float(payload.get("saved_wall_s", 0.0)), kind="q",
                    sources=qi.sigs, visible=vis)
        _explain_rcache(root, t, {"event": "peer_hit"})
        return t.select(vis) if vis else t

    def peer_export(self, key):
        """Serve a cached query entry to a peer gang in the host
        exchange format (pandas + distribution/visibility metadata);
        None on miss. The importer re-shards for its own mesh."""
        if not config.result_cache:
            return None
        with self._mu:
            e = self._entries.get(key)
            if e is None or e.kind != "q":
                return None
            try:
                from bodo_tpu.table.table import ONED
                df = e.host if e.host is not None \
                    else e.table.to_pandas()
                payload = {
                    "df": df,
                    "dist": "1D" if e.dist == ONED else "REP",
                    "visible": e.visible,
                    "saved_wall_s": e.saved_wall_s,
                }
            except Exception:  # noqa: BLE001 - export must never raise
                return None
            self._c["peer_serves"] = self._c.get("peer_serves", 0) + 1
            return payload

    def invalidate_paths(self, paths) -> int:
        """Fleet invalidation broadcast receiver: drop every entry whose
        source identities intersect ``paths`` (plus a conservative
        repr-substring match for entries without structured sources).
        Returns entries dropped; never re-broadcasts."""
        if not paths:
            return 0
        pset = {str(p) for p in paths}
        dropped = 0
        with self._mu:
            for e in list(self._entries.values()):
                if e.sources:
                    idents = {str(s[1]) for s in e.sources}
                    hit = bool(idents & pset)
                else:
                    r = repr(e.raw)
                    hit = any(p in r for p in pset)
                if hit:
                    self._drop_locked(e)
                    dropped += 1
            if dropped:
                self._c["invalidations_remote"] = \
                    self._c.get("invalidations_remote", 0) + dropped
            self._sync_grant_locked()
        # fleet-wide VIEW invalidation rides the same broadcast: any
        # registered view whose base sources intersect the mutated
        # paths goes stale on this gang too (best-effort, lazy-module)
        import sys
        vw = sys.modules.get("bodo_tpu.runtime.views")
        if vw is not None:
            try:
                vw.note_invalidated_paths(pset)
            except Exception:  # noqa: BLE001
                pass
        return dropped

    def _notify_invalidated(self, prev) -> None:
        """Tell the fleet (when hooked) which source datasets just
        invalidated a cached result, so the controller can broadcast
        and no peer serves its pre-mutation copy."""
        notify = _peer_notify
        if notify is None:
            return
        try:
            paths = tuple(str(s[1]) for s in (prev.sources or ()))
            if paths:
                notify(paths)
        except Exception:  # noqa: BLE001 - peering is best-effort
            pass

    # -- pressure / lifecycle ------------------------------------------------

    def shed_for_pressure(self) -> int:
        """Governor OOM response: push the whole device tier to host (or
        drop it) — a cache entry must never OOM a live query. Returns
        device bytes freed."""
        if not config.result_cache:
            return 0
        with self._mu:
            before = self.device_bytes
            for e in list(self._entries.values()):
                if e.table is not None:
                    self._spill_locked(e)
            self._evict_locked()
            self._sync_grant_locked()
            freed = before - self.device_bytes
            if freed > 0:
                self._c["pressure_sheds"] = \
                    self._c.get("pressure_sheds", 0) + 1
            return freed

    def reconfigure(self) -> None:
        """config.set_config hook: re-apply knobs (drop everything when
        disabled, re-enforce budgets when resized)."""
        if not config.result_cache:
            self.clear()
            return
        with self._mu:
            self._budget_cache = None
            self._evict_locked()
            self._sync_grant_locked()

    def clear(self) -> None:
        with self._mu:
            for e in list(self._entries.values()):
                self._drop_locked(e)
            self._entries.clear()
            self._by_fp.clear()
            self._by_raw.clear()
            self._refs.clear()
            self.device_bytes = 0
            self.host_bytes = 0
            self._sync_grant_locked()

    def pop(self, raw, default=None):
        """Dict-compat invalidation by RAW plan key — the fusion layer
        pops a node's entries after donating its buffers to XLA."""
        with self._mu:
            for k in list(self._by_raw.get(raw, ())):
                e = self._entries.get(k)
                if e is not None:
                    self._drop_locked(e)
            self._sync_grant_locked()
        return default

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def __contains__(self, raw) -> bool:
        with self._mu:
            return raw in self._by_raw

    def reset_stats(self) -> None:
        with self._mu:
            self._c.clear()
            self._sess.clear()
            self.saved_wall_s = 0.0

    def stats(self) -> dict:
        with self._mu:
            d = {k: int(v) for k, v in self._c.items()}
            for k in ("hits", "misses", "q_hits", "q_misses",
                      "q_incremental", "evictions", "invalidations",
                      "incremental_fallbacks", "spills", "rehydrations",
                      "rejected", "sig_uncacheable", "pressure_sheds",
                      "peer_hits", "peer_misses", "peer_serves",
                      "invalidations_remote", "partition_refresh",
                      "parts_built", "parts_reused"):
                d.setdefault(k, 0)
            dev = sum(1 for e in self._entries.values()
                      if e.table is not None)
            host = sum(1 for e in self._entries.values()
                       if e.host is not None)
            qh, qm = d["q_hits"], d["q_misses"]
            d.update(entries=len(self._entries), device_entries=dev,
                     host_entries=host, device_bytes=self.device_bytes,
                     host_bytes=self.host_bytes,
                     budget_bytes=self._device_budget(),
                     saved_wall_s=round(self.saved_wall_s, 6),
                     q_hit_rate=(qh / (qh + qm)) if (qh + qm) else 0.0,
                     enabled=bool(config.result_cache),
                     view_pins=len(self._view_pins),
                     owner_pid=self._owner_pid,
                     owner_gang=self._owner_gang)
            by_dev = self._sess_dev_locked()
            by_ent: Dict[str, int] = {}
            for e in self._entries.values():
                by_ent[e.session] = by_ent.get(e.session, 0) + 1
            by = {}
            for sid in set(self._sess) | set(by_ent):
                row = dict(self._sess.get(sid, {}))
                for k in ("q_hits", "q_misses", "hits", "misses",
                          "evicted", "records", "rejected"):
                    row.setdefault(k, 0)
                row["entries"] = by_ent.get(sid, 0)
                row["device_bytes"] = by_dev.get(sid, 0)
                by[sid] = row
            d["by_session"] = by
            return d


def _explain_rcache(root, t, info: dict) -> None:
    """EXPLAIN ANALYZE annotation for a cache-served / spliced root."""
    try:
        from bodo_tpu.utils import tracing
        if not tracing.is_tracing():
            return
        from bodo_tpu.plan import explain
        explain.record(root, rows=t.nrows,
                       wall_s=float(info.get("wall_s", 0.0)),
                       cached=info.get("event") == "hit", rcache=info)
    except Exception:  # noqa: BLE001 - observability never breaks exec
        pass


# --------------------------------------------------------------------------
# module-level singleton + façade (plan/physical.py and the observability
# layers call through these; config.set_config reaches reconfigure())
# --------------------------------------------------------------------------

_cache: Optional[ResultCache] = None
_cache_mu = threading.Lock()

# fleet peering hooks (runtime/fleet.py installs these on gang startup):
# fetch(key) -> payload dict | None asks the fingerprint's previous
# owner for its copy; notify(paths) reports a local mutation-driven
# invalidation for fleet-wide broadcast. Module-level so a test (or a
# fleet teardown) can unhook without touching the cache instance.
_peer_fetch = None
_peer_notify = None


def set_peer_hooks(fetch=None, notify=None) -> None:
    """Install (or clear, with Nones) the fleet peering hooks."""
    global _peer_fetch, _peer_notify
    with _cache_mu:
        _peer_fetch = fetch
        _peer_notify = notify


def peer_export(key):
    """Module façade: host-format payload for a cached query entry."""
    return cache().peer_export(key)


def invalidate_paths(paths) -> int:
    """Module façade: apply a fleet invalidation broadcast."""
    return cache().invalidate_paths(paths)


def cache() -> ResultCache:
    global _cache
    with _cache_mu:
        if _cache is None:
            _cache = ResultCache()
        elif (_cache._owner_pid, _cache._owner_gang) != \
                (_os.getpid(), _gang_id()):
            # ownership changed: the inherited entries hold device
            # buffers (and a governor grant) belonging to the OWNER's
            # gang — serving them here would be silent cross-process
            # sharing. A fleet gang process (its own BODO_TPU_GANG_ID,
            # exported by the controller at spawn) legitimately starts
            # its private cache without noise; a plain fork gets the
            # loud warning.
            gid = _gang_id()
            if not (gid and gid != _cache._owner_gang):
                warnings.warn(
                    f"bodo_tpu result cache: owner changed "
                    f"(pid {_cache._owner_pid} -> {_os.getpid()}, gang "
                    f"{_cache._owner_gang or '-'} -> {gid or '-'}); the "
                    f"cache is per-gang — starting a fresh empty cache. "
                    f"Fleet gang processes should carry their own "
                    f"BODO_TPU_GANG_ID (bodo_tpu.fleet sets this) and "
                    f"share results via the peering tier instead",
                    RuntimeWarning, stacklevel=2)
            _cache = ResultCache()
        return _cache


def node_key(node) -> Optional[Tuple]:
    """Semantic per-node cache key, or None (disabled / unsignable)."""
    if not config.result_cache:
        return None
    try:
        srcs = _sources_of(node)
        if srcs is None:
            return None
        sigs = []
        for kind, ident in srcs:
            s = _source_sig(kind, ident)
            if s is None:
                cache().count("sig_uncacheable")
                return None
            sigs.append((kind, ident, s))
        return ("n", _plan_fp(node), _env_key(),
                _sig_digest(tuple(sigs)))
    except Exception:  # noqa: BLE001 - keying must never fail exec
        return None


def lookup(key):
    return cache().lookup(key)


def record(key, raw, table, wall_s) -> None:
    try:
        cache().record(key, raw, table, wall_s)
    except Exception:  # noqa: BLE001
        pass


def cached_execute(root, run):
    return cache().cached_execute(root, run)


def shed_for_pressure() -> int:
    return cache().shed_for_pressure()


def reconfigure() -> None:
    cache().reconfigure()


def clear() -> None:
    cache().clear()


def stats() -> dict:
    return cache().stats()


def reset_stats() -> None:
    cache().reset_stats()
