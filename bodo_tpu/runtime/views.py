"""Materialized views & continuous queries: an incremental view DAG on
the serving path.

Named views register a logical plan whose materialization lives in the
semantic result cache (runtime/result_cache.py) under the plan's own
query key. Views reference other views as scan sources (plan.logical
ViewScan), so a cached daily aggregate feeds coarser rollups; a view
scan signs with the view's BASE source signatures, which makes every
dependent's cache key roll over exactly when the underlying data
changes — maintenance then propagates topologically:

  * append to a base table   -> the leaf view's entry splices a delta
                                scan (PR 13 machinery: classify_change /
                                _try_incremental), including in-place
                                grown files (#rg= fragments);
  * mutate of SOME files     -> partition-level invalidation: the
                                entry's per-source-file contribution map
                                re-runs only the affected files' delta
                                plans (_try_partition_refresh);
  * anything ambiguous       -> full invalidation, full recompute —
                                never a stale partial;
  * interior views           -> re-aggregate from their parents' cached
                                materializations (a plain execute whose
                                leaf scans serve at cache speed).

Continuous queries: sessions register standing queries
(``session.subscribe(view, max_staleness_s=)``); idle scheduler workers
poll ``maintenance_due()`` between queue drains, and a detected change
schedules refreshes as ordinary weighted-fair work on the system
maintenance session (tenants are not billed for shared refreshes).
Refreshed results are delivered to subscribers through the same serve
futures every query uses, with per-view staleness tracking.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from bodo_tpu.config import config
from bodo_tpu.utils.logging import log

#: session id every view refresh is attributed to (result-cache
#: by_session rows, scheduler accounting) — tenants are not billed
MAINTENANCE_SESSION = "__maintenance__"

_STALENESS_SAMPLES = 256   # per-view staleness history for the p99


class ViewError(ValueError):
    """Typed registry error (unknown/duplicate view, live dependents)."""


class _View:
    def __init__(self, name: str, plan, root, deps: Tuple[str, ...]):
        self.name = name
        self.plan = plan            # user's logical root (pre-optimize)
        self.root = root            # optimized exec root (stable fp)
        self.schema = dict(root.schema)
        self.deps = deps            # direct parent view names
        self.dependents: set = set()
        self.version = 0            # bumps when a refresh changed data
        self.fp = None              # result-cache plan fingerprint
        self.last_sig_digest = None
        self.base_sigs = None       # qi.sigs snapshot at materialize
        self.lock = threading.RLock()
        self.subs: List["Subscription"] = []
        self.stale_since: Optional[float] = None  # monotonic, watcher
        self.inflight = False
        self.staleness = deque(maxlen=_STALENESS_SAMPLES)
        self.refreshes_full = 0
        self.refreshes_incremental = 0
        self.full_wall_s = 0.0
        self.refresh_wall_s = 0.0


class Subscription:
    """A standing query on one view. ``next(timeout)`` blocks for the
    next refresh and returns the refreshed Table (the underlying
    delivery is the maintenance query's serve Future)."""

    def __init__(self, view_name: str, session_id: str,
                 max_staleness_s: Optional[float]):
        self.view = view_name
        self.session_id = session_id
        self.max_staleness_s = max_staleness_s
        self._cv = threading.Condition()
        self._futures: deque = deque()
        self.cancelled = False

    def _deliver(self, fut) -> None:
        with self._cv:
            if self.cancelled:
                return
            self._futures.append(fut)
            self._cv.notify_all()

    def next(self, timeout: Optional[float] = None):
        """Block until the next refresh lands; returns the refreshed
        Table. Raises TimeoutError when nothing arrives in time."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._cv:
            while not self._futures:
                if self.cancelled:
                    raise ViewError(
                        f"subscription on {self.view!r} cancelled")
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    raise TimeoutError(
                        f"no refresh of view {self.view!r} within "
                        f"{timeout}s")
                self._cv.wait(left if left is not None else 0.5)
            fut = self._futures.popleft()
        left = None if deadline is None else \
            max(deadline - time.monotonic(), 0.01)
        return fut.result(timeout=left)

    def cancel(self) -> None:
        with self._cv:
            self.cancelled = True
            self._cv.notify_all()
        _unsubscribe(self)


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

_mu = threading.RLock()
_views: Dict[str, _View] = {}
_c: Dict[str, int] = {}

# watcher state: read LOCK-FREE by idle scheduler workers holding the
# scheduler condition (maintenance_due below) — plain attribute writes
# only, never guarded reads
_next_poll_at = 0.0
_n_subs = 0
_tick_mu = threading.Lock()


def _count(name: str, n: int = 1) -> None:
    with _mu:
        _c[name] = _c.get(name, 0) + n


def _get(name: str) -> _View:
    with _mu:
        v = _views.get(name)
    if v is None:
        raise ViewError(f"unknown view {name!r}")
    return v


def _view_scans(node, out=None):
    from bodo_tpu.plan import logical as L
    if out is None:
        out = []
    if isinstance(node, L.ViewScan):
        out.append(node)
    for c in node.children:
        _view_scans(c, out)
    return out


def _clear_cached(node) -> None:
    """Drop plan-collapse memoization across a held plan tree: a view's
    root is executed repeatedly over CHANGING data, so node._cached
    tables from the previous generation must never short-circuit."""
    node._cached = None
    for c in node.children:
        _clear_cached(c)


def _as_plan(plan):
    """Accept a logical Node or anything carrying one (BodoDataFrame)."""
    from bodo_tpu.plan import logical as L
    if isinstance(plan, L.Node):
        return plan
    inner = getattr(plan, "_plan", None)
    if isinstance(inner, L.Node):
        return inner
    raise TypeError(f"create_view needs a logical plan or a lazy "
                    f"frame, got {type(plan).__name__}")


def create_view(name: str, plan) -> None:
    """Register a named materialized view over ``plan`` (a logical plan
    root or a lazy BodoDataFrame). The plan may scan other views
    (``views.read(name)``); every referenced view must already exist, so
    the registry is a DAG by construction. Materialization is lazy —
    the first read (or the first maintenance refresh) pays it."""
    from bodo_tpu.plan.optimizer import optimize
    root = _as_plan(plan)
    parents = tuple(dict.fromkeys(s.name for s in _view_scans(root)))
    with _mu:
        if name in _views:
            raise ViewError(f"view {name!r} already exists")
        for p in parents:
            if p not in _views:
                raise ViewError(f"view {name!r} references unknown "
                                f"view {p!r}")
        v = _View(name, root, optimize(root), deps=parents)
        _views[name] = v
        for p in parents:
            _views[p].dependents.add(name)
    for p in parents:
        _sync_pin(p)
    _count("created")
    log(1, f"views: created {name!r} over "
           f"{parents or 'base tables'}")


def drop_view(name: str) -> None:
    """Unregister a view; refuses while downstream views depend on it.
    Live subscriptions are cancelled."""
    with _mu:
        v = _views.get(name)
        if v is None:
            raise ViewError(f"unknown view {name!r}")
        if v.dependents:
            raise ViewError(f"view {name!r} has dependents "
                            f"{sorted(v.dependents)}")
        del _views[name]
        for p in v.deps:
            pv = _views.get(p)
            if pv is not None:
                pv.dependents.discard(name)
        subs = list(v.subs)
        v.subs.clear()
    for s in subs:
        with s._cv:
            s.cancelled = True
            s._cv.notify_all()
    _recount_subs()
    for p in v.deps:
        _sync_pin(p)
    if v.fp is not None:
        _rcache().set_view_pin(v.fp, 0)


def list_views() -> List[str]:
    with _mu:
        return sorted(_views)


def scan_node(name: str):
    """A fresh ViewScan leaf for composing this view into a plan."""
    from bodo_tpu.plan import logical as L
    v = _get(name)
    return L.ViewScan(name, v.schema, version=v.version)


def read(name: str):
    """Lazy frame over the view — compose/filter/aggregate like any
    table; execution serves the materialization from the result cache."""
    from bodo_tpu.pandas_api.frame import BodoDataFrame
    return BodoDataFrame(scan_node(name))


def base_sources(name: str):
    """The view's transitive BASE sources in result-cache form
    (tuple of ("pq"|"csv"|"mem", ident)) — what a ViewScan signs as.
    None when any leaf is unsignable."""
    from bodo_tpu.runtime import result_cache as rcache
    v = _get(name)
    out, seen = [], set()

    def walk(view: _View) -> bool:
        srcs = rcache._sources_of(view.root)
        if srcs is None:
            return False
        for s in srcs:
            if s not in seen:
                seen.add(s)
                out.append(s)
        return True

    # _sources_of resolves nested ViewScans back through this function,
    # so walking the root alone already covers the transitive closure
    return tuple(out) if walk(v) else None


# --------------------------------------------------------------------------
# materialization / maintenance
# --------------------------------------------------------------------------

def _rcache():
    from bodo_tpu.runtime import result_cache
    return result_cache.cache()


def _sync_pin(name: str) -> None:
    """Benefit-eviction pin: weight the view's cache entry by its live
    dependent count (downstream views + subscriptions)."""
    with _mu:
        v = _views.get(name)
        if v is None or v.fp is None:
            return
        deps = len(v.dependents) + len(v.subs)
        fp = v.fp
    _rcache().set_view_pin(fp, deps)


def materialized_table(name: str):
    """Current materialization of a view as a Table — the ViewScan
    execution hook (plan/physical.py). Always goes through the cached
    execute boundary: unchanged data is a device cache hit, an append
    splices, a partition mutate re-merges, anything else recomputes."""
    return _materialize(_get(name))


def refresh(name: str):
    """Synchronously bring one view (and its ancestors) up to date."""
    return _materialize(_get(name))


def _materialize(v: _View):
    from bodo_tpu.plan import physical
    from bodo_tpu.runtime import result_cache as rcache
    with v.lock:
        # parents first: this view's execution reads their
        # materializations through ViewScan leaves
        for p in v.deps:
            _materialize(_get(p))
        cache = _rcache()
        before = cache.stats()
        detected = v.stale_since
        _clear_cached(v.root)
        t0 = time.perf_counter()
        t = physical.execute(v.root, optimize_first=False)
        wall = time.perf_counter() - t0
        after = cache.stats()
        with rcache.signature_epoch():
            try:
                qi = cache._query_info(v.root)
            except Exception:  # noqa: BLE001
                qi = None
        changed = qi is not None and \
            qi.key[3] != v.last_sig_digest
        # hit-detection rides the sig digest, NOT q_hits deltas: this
        # view's execute re-enters its parents' ViewScans, and their
        # (expected) cache hits would read as ours
        hit = qi is not None and not changed
        incremental = (after["q_incremental"] >
                       before["q_incremental"]) or \
            (after["partition_refresh"] > before["partition_refresh"])
        if qi is not None:
            if v.fp is None:
                v.fp = qi.fp
            v.base_sigs = qi.sigs
            if changed:
                v.version += 1
                v.last_sig_digest = qi.key[3]
            if not hit:
                if incremental:
                    v.refreshes_incremental += 1
                    v.refresh_wall_s += wall
                else:
                    v.refreshes_full += 1
                    v.full_wall_s += wall
                # contribution map for partition-level invalidation,
                # rebuilt per generation (bounded by view_max_parts)
                try:
                    cache.build_parts(
                        qi.key, physical._exec,
                        max_parts=int(config.view_max_parts))
                except Exception:  # noqa: BLE001
                    pass
        if changed or v.stale_since is not None:
            v.stale_since = None
            if detected is not None:
                v.staleness.append(
                    max(time.monotonic() - detected, 0.0))
        _sync_pin(v.name)
        return t


# --------------------------------------------------------------------------
# continuous queries: subscriptions + the signature watcher
# --------------------------------------------------------------------------

def subscribe(view: str, *, session=None,
              max_staleness_s: Optional[float] = None) -> Subscription:
    """Register a standing query; used via ``Session.subscribe``. The
    subscriber receives every subsequent refresh of the view through
    ``Subscription.next()``."""
    v = _get(view)
    sid = getattr(session, "sid", None) or "-"
    sub = Subscription(view, sid, max_staleness_s)
    with _mu:
        v.subs.append(sub)
    _recount_subs()
    _sync_pin(view)
    _wake_watcher()   # poll promptly for tight staleness bounds
    return sub


def _unsubscribe(sub: Subscription) -> None:
    with _mu:
        v = _views.get(sub.view)
        if v is not None and sub in v.subs:
            v.subs.remove(sub)
    _recount_subs()
    if v is not None:
        _sync_pin(v.name)


def _recount_subs() -> None:
    global _n_subs
    with _mu:
        _n_subs = sum(len(v.subs) for v in _views.values())


def note_invalidated_paths(paths) -> int:
    """Result-cache invalidation hook (local mutate or a fleet
    ``invalidate`` broadcast): flag every view whose base sources
    intersect ``paths`` as stale, so the next watcher tick (or read)
    refreshes it. Returns views flagged."""
    pset = {str(p) for p in paths}
    flagged = 0
    now = time.monotonic()
    with _mu:
        views = list(_views.values())
    for v in views:
        try:
            srcs = base_sources(v.name)
        except Exception:  # noqa: BLE001
            srcs = None
        if srcs is None:
            continue
        idents = {str(s[1]) for s in srcs}
        # dataset idents are dirs/globs; broadcast paths are files —
        # prefix/containment matches both directions
        hit = bool(idents & pset) or any(
            p.startswith(i.rstrip("/*") + "/") or i in p
            for p in pset for i in idents)
        if hit and v.stale_since is None:
            v.stale_since = now
            flagged += 1
    if flagged:
        _count("flagged_stale", flagged)
        _wake_watcher()
    return flagged


def _wake_watcher() -> None:
    """Writers take _mu; maintenance_due() stays a lock-free read (it
    runs holding the scheduler condition — see scheduler._worker)."""
    global _next_poll_at
    with _mu:
        _next_poll_at = 0.0


def _arm_next_poll() -> None:
    global _next_poll_at
    nxt = time.monotonic() + _poll_interval_s()
    with _mu:
        _next_poll_at = nxt


def _poll_interval_s() -> float:
    base = max(float(config.view_poll_s), 0.05)
    with _mu:
        bounds = [s.max_staleness_s for v in _views.values()
                  for s in v.subs if s.max_staleness_s]
    if bounds:
        base = min(base, max(min(bounds) / 4.0, 0.05))
    return base


def maintenance_due() -> bool:
    """Lock-free check idle scheduler workers run while holding the
    scheduler condition: is it time for a watcher poll?"""
    return _n_subs > 0 and time.monotonic() >= _next_poll_at


def maintenance_tick(sched) -> None:
    """One watcher poll (outside every lock the scheduler holds):
    detect changed base signatures, then schedule a refresh of each
    stale subscribed view as weighted-fair work on the system
    maintenance session. Rejections (queue full, degraded) leave the
    view flagged — the next tick retries."""
    if not _tick_mu.acquire(blocking=False):
        return  # another idle worker is already polling
    try:
        _arm_next_poll()
        _count("ticks")
        from bodo_tpu.runtime import result_cache as rcache
        now = time.monotonic()
        with _mu:
            views = [v for v in _views.values() if v.subs]
        for v in views:
            if v.stale_since is None and v.base_sigs is not None:
                # signature watcher: one stat pass per source
                with rcache.signature_epoch():
                    for kind, ident, _old in v.base_sigs:
                        if rcache._source_sig(kind, ident) != _old:
                            v.stale_since = now
                            _count("detected_stale")
                            break
            if v.stale_since is None or v.inflight:
                continue
            self_v = v

            def job(v=self_v):
                try:
                    return _materialize(v)
                finally:
                    v.inflight = False

            try:
                sess = sched.session(
                    MAINTENANCE_SESSION,
                    priority=float(config.view_maintenance_weight))
                v.inflight = True
                fut = sess.submit(job)
            except Exception:  # noqa: BLE001 - typed rejection: retry
                v.inflight = False
                _count("refresh_rejected")
                continue
            _count("refresh_scheduled")
            with _mu:
                subs = list(v.subs)
            for sub in subs:
                sub._deliver(fut)
    finally:
        _tick_mu.release()


# --------------------------------------------------------------------------
# observability / lifecycle
# --------------------------------------------------------------------------

def _depth(v: _View, memo: Dict[str, int]) -> int:
    got = memo.get(v.name)
    if got is not None:
        return got
    d = 1 + max((_depth(_views[p], memo) for p in v.deps
                 if p in _views), default=0)
    memo[v.name] = d
    return d


def stats() -> dict:
    """Registry + maintenance stats (telemetry/doctor/metrics read
    through this; lazy-module rule applies on their side)."""
    with _mu:
        memo: Dict[str, int] = {}
        by = {}
        lagging, lag_p99 = None, -1.0
        ref_wall = full_wall = 0.0
        n_inc = n_full = 0
        for name, v in sorted(_views.items()):
            hist = sorted(v.staleness)
            p99 = hist[min(int(len(hist) * 0.99),
                           len(hist) - 1)] if hist else 0.0
            cur = (time.monotonic() - v.stale_since) \
                if v.stale_since is not None else 0.0
            worst = max(p99, cur)
            if worst > lag_p99:
                lagging, lag_p99 = name, worst
            ref_wall += v.refresh_wall_s
            full_wall += v.full_wall_s
            n_inc += v.refreshes_incremental
            n_full += v.refreshes_full
            by[name] = {
                "version": v.version,
                "depth": _depth(v, memo),
                "deps": sorted(v.deps),
                "dependents": sorted(v.dependents),
                "subscriptions": len(v.subs),
                "stale": v.stale_since is not None,
                "staleness_p99_s": round(p99, 6),
                "refreshes_incremental": v.refreshes_incremental,
                "refreshes_full": v.refreshes_full,
            }
        out = {k: int(n) for k, n in _c.items()}
        n_ref = n_inc + max(n_full - len(by), 0)  # first fulls excluded
        out.update(
            n_views=len(by),
            dag_depth=max(memo.values(), default=0),
            subscriptions=_n_subs,
            refreshes_incremental=n_inc,
            refreshes_full=n_full,
            # refresh cost relative to full recompute cost (the bench
            # bar: <= 0.10); 0.0 until a refresh has happened
            refresh_ratio=round(ref_wall / full_wall, 6)
            if full_wall > 0 and n_ref > 0 else 0.0,
            staleness_p99_s=round(max(lag_p99, 0.0), 6),
            lagging_view=lagging,
            by_view=by,
        )
        return out


def reset() -> None:
    """Tests: drop every view, subscription, pin and counter."""
    global _next_poll_at, _n_subs
    with _mu:
        views = list(_views.values())
        _views.clear()
        _c.clear()
        _n_subs = 0
        _next_poll_at = 0.0
    for v in views:
        for s in v.subs:
            with s._cv:
                s.cancelled = True
                s._cv.notify_all()
    try:
        _rcache().clear_view_pins()
    except Exception:  # noqa: BLE001
        pass
