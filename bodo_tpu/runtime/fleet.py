"""Fleet serving: one controller, many gang processes, peered caches.

PR 14 (runtime/scheduler.py) made the engine a serving system but caps
it at exactly one in-process gang. This module is the Pathways-style
single-controller shape over N of them (PAPERS §2; Ray's control plane
fronting many workers, PAPERS §5): a controller in the client process
spawns N **gang processes**, each running the PR 14 scheduler behind
its telemetry endpoint, and multiplexes many logical sessions over the
fleet through a small length-prefixed wire protocol.

WIRE PROTOCOL (stdlib sockets): every frame is a 5-byte header
``struct.pack(">IB", len(body), kind)`` followed by the body. Kind
``J`` is a UTF-8 JSON object (control plane), kind ``P`` is a pickle
(cloudpickle for thunks, plain payloads for results — the data plane).
A header whose length exceeds ``config.fleet_frame_max`` is a typed
:class:`ProtocolError` before any allocation; EOF mid-frame is a typed
``truncated frame``. One TCP connection carries one request/response
exchange. Ops: ``ping``, ``open``, ``submit`` (header frame + pickled
thunk frame; the gang streams back an ``ack`` frame at enqueue and a
``result`` frame — + pickled payload on success — at completion, so a
gang dying mid-query is an observable mid-stream EOF, not a hang),
``close``, ``peer_get`` (+ pickled cache key), ``invalidate``,
``stats``, ``shutdown``.

ROUTING: plan/routing keys map to gangs by consistent hashing (64
virtual nodes per gang) so result/plan-cache locality survives
scale-out and a gang join/leave moves only ~1/N of the keyspace. The
routing key defaults to a digest of the cloudpickled thunk — a
repeat-issued query template lands on the same gang every time; callers
with a real plan fingerprint can pass it explicitly.

ADMISSION: a scrape thread GETs each gang's ``/metrics`` + ``/healthz``
every ``config.fleet_scrape_s`` and runs the SAME admission decision
the gang would make locally (``signals_from_health`` merged with
``signals_from_metrics`` — built for exactly this remote-twin use).
Submits route around shed/degraded/backed-off gangs to the next ring
successor; a gang failing ``config.fleet_dead_scrapes`` consecutive
scrapes (or observed dead at submit time) is evicted from the ring.
When no gang is serviceable the client gets the healthiest gang's typed
rejection with its retry hint — never a hang.

CACHE PEERING: on a local result-cache q-miss the owning gang asks the
routing key's PREVIOUS owner (the previous ring's owner after a
membership change, else the ring successor) for its copy over
``peer_get`` before recomputing (result_cache.set_peer_hooks). Dataset
mutations invalidate fleet-wide: when a gang's cache drops a stale
entry, the mutated source paths ride the submit response back to the
controller, which broadcasts ``invalidate`` to every other gang — no
peer ever serves a pre-mutation result.

SLO CLASSES + QUOTAS: sessions carry ``slo="latency"|"throughput"``
end-to-end (the gang scheduler ages latency-class queues
``config.serve_latency_boost``× faster) and the controller enforces a
per-session in-flight quota (``config.fleet_session_quota``) as a typed
``Overloaded(reason="session_quota")``.

Everything here is stdlib-only at import time (sockets, json, struct,
urllib); jax lives in the gang processes. ``bodo_tpu.fleet`` is the
client façade.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from bisect import bisect_right
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from bodo_tpu.config import config
from bodo_tpu.runtime.scheduler import (
    AdmissionController,
    BackOff,
    Degraded,
    Overloaded,
    QueryFailed,
    ServeRejection,
    signals_from_health,
    signals_from_metrics,
)
from bodo_tpu.utils.logging import log

__all__ = [
    "ProtocolError", "FleetController", "FleetSession", "RemoteFleet",
    "start", "stop", "controller", "controller_stats", "reconfigure",
    "connect", "gang_main",
]


class ProtocolError(RuntimeError):
    """Malformed wire traffic: truncated frame, oversized header, bad
    kind byte, or a JSON/pickle body that does not decode."""


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

_HDR = struct.Struct(">IB")
_KIND_JSON = ord("J")
_KIND_PICKLE = ord("P")


def _frame_max() -> int:
    try:
        return max(int(config.fleet_frame_max), 1 << 16)
    except Exception:  # noqa: BLE001
        return 64 << 20


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = io.BytesIO()
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ProtocolError(
                f"truncated frame: peer closed after {got}/{n} bytes")
        buf.write(chunk)
        got += len(chunk)
    return buf.getvalue()


def _send_frame(sock: socket.socket, kind: int, body: bytes) -> None:
    sock.sendall(_HDR.pack(len(body), kind) + body)


def _recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    hdr = _recv_exact(sock, _HDR.size)
    length, kind = _HDR.unpack(hdr)
    if kind not in (_KIND_JSON, _KIND_PICKLE):
        raise ProtocolError(f"unknown frame kind {kind:#x}")
    if length > _frame_max():
        # reject BEFORE allocating: an adversarial header must not be
        # able to balloon the receiver
        raise ProtocolError(
            f"oversized frame: {length} bytes > fleet_frame_max "
            f"{_frame_max()}")
    return kind, _recv_exact(sock, length)


def _send_json(sock: socket.socket, obj: dict) -> None:
    _send_frame(sock, _KIND_JSON,
                json.dumps(obj, default=str).encode("utf-8"))


def _recv_json(sock: socket.socket) -> dict:
    kind, body = _recv_frame(sock)
    if kind != _KIND_JSON:
        raise ProtocolError("expected a JSON frame")
    try:
        out = json.loads(body.decode("utf-8"))
    except Exception as e:  # noqa: BLE001
        raise ProtocolError(f"bad JSON frame: {e}") from None
    if not isinstance(out, dict):
        raise ProtocolError("JSON frame is not an object")
    return out


def _send_pickle(sock: socket.socket, obj) -> None:
    import cloudpickle
    _send_frame(sock, _KIND_PICKLE, cloudpickle.dumps(obj))


def _recv_pickle(sock: socket.socket):
    kind, body = _recv_frame(sock)
    if kind != _KIND_PICKLE:
        raise ProtocolError("expected a pickle frame")
    try:
        return pickle.loads(body)
    except Exception as e:  # noqa: BLE001
        raise ProtocolError(f"bad pickle frame: {e}") from None


def _connect(addr: str, timeout: float = 10.0) -> socket.socket:
    host, port = addr.rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=timeout)
    # multi-frame exchanges (submit = header + thunk) must not sit in
    # Nagle's buffer waiting for a delayed ACK
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


# typed-rejection transport: exceptions cross the wire as
# {"etype", "msg", "reason", "retry_after_s"} and are reconstructed as
# the SAME types client-side, so the PR 14 backpressure contract holds
# end-to-end across the fleet.
_ETYPES = {"Overloaded": Overloaded, "Degraded": Degraded,
           "BackOff": BackOff, "ServeRejection": ServeRejection}


def _exc_to_wire(e: BaseException) -> dict:
    if isinstance(e, ServeRejection):
        return {"ok": False, "etype": type(e).__name__, "msg": str(e),
                "reason": e.reason, "retry_after_s": e.retry_after_s}
    if isinstance(e, QueryFailed):
        return {"ok": False, "etype": "QueryFailed", "msg": str(e),
                "session": e.session_id, "qid": e.query_id,
                "cause": f"{type(e.__cause__).__name__}: {e.__cause__}"
                if e.__cause__ else ""}
    return {"ok": False, "etype": "QueryFailed", "msg": str(e),
            "cause": f"{type(e).__name__}: {e}"}


def _exc_from_wire(d: dict, *, sid: str = "-",
                   qid: Optional[str] = None) -> BaseException:
    et = d.get("etype", "")
    if et in _ETYPES:
        return _ETYPES[et](d.get("msg", et),
                           retry_after_s=float(d.get("retry_after_s",
                                                     0.0)),
                           reason=d.get("reason", ""))
    cause = RuntimeError(d.get("cause") or d.get("msg", "remote error"))
    return QueryFailed(d.get("session", sid), d.get("qid", qid), cause)


# ---------------------------------------------------------------------------
# consistent-hash ring
# ---------------------------------------------------------------------------

class _Ring:
    """Consistent-hash ring with virtual nodes. Membership changes
    snapshot the previous point list so the fingerprint's PREVIOUS
    owner (the peer most likely to hold a migrated key's cache entry)
    stays derivable for one generation."""

    def __init__(self, vnodes: int = 64):
        self._vnodes = max(int(vnodes), 1)
        self._points: List[Tuple[int, str]] = []
        self._prev: Optional[List[Tuple[int, str]]] = None
        self._members: List[str] = []

    @staticmethod
    def _h(s: str) -> int:
        return int.from_bytes(
            hashlib.sha256(s.encode("utf-8")).digest()[:8], "big")

    def members(self) -> List[str]:
        return list(self._members)

    def add(self, gid: str) -> None:
        if gid in self._members:
            return
        self._prev = list(self._points)
        self._members.append(gid)
        self._points.extend((self._h(f"{gid}#{i}"), gid)
                            for i in range(self._vnodes))
        self._points.sort()

    def remove(self, gid: str) -> None:
        if gid not in self._members:
            return
        self._prev = list(self._points)
        self._members.remove(gid)
        self._points = [p for p in self._points if p[1] != gid]

    @staticmethod
    def _owner_in(points: List[Tuple[int, str]], h: int) -> Optional[str]:
        if not points:
            return None
        i = bisect_right(points, (h, "￿")) % len(points)
        return points[i][1]

    def owner(self, key: str) -> Optional[str]:
        return self._owner_in(self._points, self._h(key))

    def successors(self, key: str) -> List[str]:
        """Distinct gangs in ring order starting at the key's owner —
        the routing preference list."""
        if not self._points:
            return []
        h = self._h(key)
        i = bisect_right(self._points, (h, "￿"))
        seen: List[str] = []
        n = len(self._points)
        for j in range(n):
            gid = self._points[(i + j) % n][1]
            if gid not in seen:
                seen.append(gid)
        return seen

    def prev_owner(self, key: str) -> Optional[str]:
        """Designated peering target: the previous ring generation's
        owner when it differs from the current one (the gang that held
        the key before a join/leave), else the current ring successor."""
        cur = self.owner(key)
        if self._prev is not None:
            old = self._owner_in(self._prev, self._h(key))
            if old is not None and old != cur and old in self._members:
                return old
        succ = self.successors(key)
        for gid in succ[1:]:
            return gid
        return None


# ---------------------------------------------------------------------------
# gang process side
# ---------------------------------------------------------------------------

_tls = threading.local()


def _gang_peer_fetch(key):
    """result_cache fetch hook (runs on the gang's scheduler worker
    thread): ask the controller-designated peer for its copy of this
    cache key. The hint is per-query, set by the submit wrapper."""
    addr = getattr(_tls, "peer_addr", None)
    if not addr:
        return None
    try:
        with _connect(addr, timeout=10.0) as s:
            _send_json(s, {"op": "peer_get"})
            _send_pickle(s, key)
            head = _recv_json(s)
            if not head.get("found"):
                return None
            return _recv_pickle(s)
    except Exception as e:  # noqa: BLE001 - peering is best-effort
        log(2, f"fleet: peer_get from {addr} failed: "
               f"{type(e).__name__}: {e}")
        return None


def _gang_peer_notify(paths) -> None:
    """result_cache notify hook: collect mutation-invalidated source
    paths into the per-query box; they ride the submit response back to
    the controller for fleet-wide broadcast."""
    box = getattr(_tls, "inval_box", None)
    if box is not None:
        for p in paths:
            if p not in box:
                box.append(p)


def _wrap_thunk(fn: Callable, peer_addr: Optional[str],
                inval_box: list) -> Callable:
    def wrapped():
        _tls.peer_addr = peer_addr
        _tls.inval_box = inval_box
        try:
            return fn()
        finally:
            _tls.peer_addr = None
            _tls.inval_box = None
    return wrapped


def _gang_handle(conn: socket.socket, gang_id: str) -> None:
    """One request/response exchange on an accepted connection."""
    from bodo_tpu.runtime import result_cache as rcache
    from bodo_tpu.runtime import scheduler as sched_mod
    try:
        req = _recv_json(conn)
    except ProtocolError as e:
        # hostile/truncated input: answer typed when the socket still
        # works, then drop the connection — never take the gang down
        try:
            _send_json(conn, {"ok": False, "etype": "ProtocolError",
                              "msg": str(e)})
        except Exception:  # noqa: BLE001
            pass
        return
    op = req.get("op")
    if op == "ping":
        _send_json(conn, {"ok": True, "gang_id": gang_id,
                          "pid": os.getpid()})
    elif op == "open":
        sched_mod.scheduler().session(
            req.get("sid"), priority=float(req.get("weight", 1.0)),
            allow_degraded=bool(req.get("allow_degraded", False)),
            slo=req.get("slo", "throughput"))
        _send_json(conn, {"ok": True, "gang_id": gang_id})
    elif op == "close":
        sch = sched_mod.scheduler()
        s = sch._sessions.get(req.get("sid"))
        if s is not None:
            sch.close_session(s)
        _send_json(conn, {"ok": True})
    elif op == "submit":
        _gang_handle_submit(conn, req, gang_id)
    elif op == "peer_get":
        key = _recv_pickle(conn)
        payload = None
        try:
            payload = rcache.peer_export(key)
        except Exception:  # noqa: BLE001
            payload = None
        if payload is None:
            _send_json(conn, {"found": False})
        else:
            _send_json(conn, {"found": True})
            _send_pickle(conn, payload)
    elif op == "invalidate":
        n = 0
        try:
            n = rcache.invalidate_paths(req.get("paths") or [])
        except Exception:  # noqa: BLE001
            pass
        _send_json(conn, {"ok": True, "dropped": int(n)})
    elif op == "stats":
        out = {"ok": True, "gang_id": gang_id, "pid": os.getpid()}
        try:
            out["scheduler"] = sched_mod.scheduler().stats()
        except Exception:  # noqa: BLE001
            pass
        try:
            out["result_cache"] = {
                k: v for k, v in rcache.stats().items()
                if isinstance(v, (int, float, str, bool))}
        except Exception:  # noqa: BLE001
            pass
        _send_json(conn, out)
    elif op == "shutdown":
        _send_json(conn, {"ok": True})
        try:
            conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        os._exit(0)
    else:
        _send_json(conn, {"ok": False, "etype": "ProtocolError",
                          "msg": f"unknown op {op!r}"})


def _gang_handle_submit(conn: socket.socket, req: dict,
                        gang_id: str) -> None:
    from bodo_tpu.runtime import resilience
    from bodo_tpu.runtime import scheduler as sched_mod
    sid = req.get("sid") or "default"
    qid = req.get("qid")
    try:
        fn = _recv_pickle(conn)
    except ProtocolError as e:
        _send_json(conn, {"ok": False, "etype": "ProtocolError",
                          "msg": str(e)})
        return
    inval_box: list = []
    sch = sched_mod.scheduler()
    session = sch.session(
        sid, priority=float(req.get("weight", 1.0)),
        allow_degraded=bool(req.get("allow_degraded", False)),
        slo=req.get("slo", "throughput"))
    try:
        fut = session.submit(
            _wrap_thunk(fn, req.get("peer"), inval_box))
    except (ServeRejection, QueryFailed) as e:
        _send_json(conn, _exc_to_wire(e))
        return
    # enqueue acknowledged: from here on the client is mid-stream, so
    # a dying gang is an observable EOF instead of a silent hang. The
    # chaos injection point sits exactly here — after the ack, before
    # the result — to exercise that path.
    _send_json(conn, {"ev": "ack", "qid": qid, "gang_id": gang_id})
    resilience.maybe_inject("fleet.serve")
    try:
        result = fut.result(timeout=600.0)
    except (ServeRejection, QueryFailed) as e:
        _send_json(conn, dict(_exc_to_wire(e), ev="result",
                              invalidated=inval_box))
        return
    except Exception as e:  # noqa: BLE001
        _send_json(conn, dict(_exc_to_wire(e), ev="result",
                              invalidated=inval_box))
        return
    _send_json(conn, {"ev": "result", "ok": True, "qid": qid,
                      "invalidated": inval_box})
    _send_pickle(conn, result)


def _watch_parent() -> None:
    """Exit when the controller goes away: stdin is the controller's
    pipe; EOF means the parent died or dropped us."""
    try:
        while True:
            b = sys.stdin.buffer.read(1)
            if not b:
                break
    except Exception:  # noqa: BLE001
        pass
    os._exit(0)


def gang_main() -> None:
    """Entry point of a fleet gang process (spawned by the controller):
    bring up the local scheduler + telemetry endpoint + peering hooks,
    write the ready file, then serve the wire protocol forever."""
    gang_id = os.environ.get("BODO_TPU_GANG_ID") \
        or f"gang-{os.getpid()}"
    os.environ["BODO_TPU_GANG_ID"] = gang_id
    ready_path = os.environ.get("BODO_TPU_FLEET_READY", "")

    from bodo_tpu.runtime import result_cache as rcache
    from bodo_tpu.runtime import scheduler as sched_mod
    from bodo_tpu.runtime import telemetry
    rcache.set_peer_hooks(fetch=_gang_peer_fetch,
                          notify=_gang_peer_notify)
    sched_mod.scheduler()._ensure_workers()
    telem_addr = telemetry.serve(0)

    srv = socket.create_server(("127.0.0.1", 0))
    srv.listen(64)
    port = srv.getsockname()[1]

    threading.Thread(target=_watch_parent, daemon=True,
                     name="fleet-parent-watch").start()

    if ready_path:
        tmp = ready_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"gang_id": gang_id, "pid": os.getpid(),
                       "serve_addr": f"127.0.0.1:{port}",
                       "telemetry_addr": telem_addr}, f)
        os.replace(tmp, ready_path)
    log(1, f"fleet gang {gang_id} serving on 127.0.0.1:{port} "
           f"(telemetry {telem_addr})")

    while True:
        try:
            conn, _ = srv.accept()
        except OSError:
            break
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

        def _run(c=conn):
            try:
                _gang_handle(c, gang_id)
            except Exception as e:  # noqa: BLE001 - one bad conn only
                log(2, f"fleet gang {gang_id}: connection error: "
                       f"{type(e).__name__}: {e}")
            finally:
                try:
                    c.close()
                except OSError:
                    pass

        threading.Thread(target=_run, daemon=True).start()


# ---------------------------------------------------------------------------
# controller side
# ---------------------------------------------------------------------------

import re as _re

_PEER_HITS_RE = _re.compile(
    r'bodo_tpu_result_cache_events_total\{[^}]*event="peer_hits"'
    r'[^}]*\}\s+([0-9.eE+-]+)')


class _GangState:
    __slots__ = ("gang_id", "proc", "serve_addr", "telemetry_addr",
                 "state", "reason", "retry_after_s", "fail_scrapes",
                 "admission", "stdin", "peer_hits", "capacity_frac")

    def __init__(self, gang_id: str):
        self.gang_id = gang_id
        self.proc: Optional[subprocess.Popen] = None
        self.serve_addr = ""
        self.telemetry_addr = ""
        self.state = "ok"           # ok|shed|degraded|backoff|dead
        self.reason = ""
        self.retry_after_s = 0.0
        self.fail_scrapes = 0
        self.peer_hits = 0
        # surviving-rank fraction scraped from /healthz["elastic"]; a
        # shrunk gang (< 1.0) keeps serving but at reduced throughput,
        # so quota and routing scale by it rather than evicting.
        self.capacity_frac = 1.0
        # one admission twin PER GANG: the pressure-event memory (last
        # OOM/shed counters) is per-scrape-target state
        self.admission = AdmissionController()
        self.stdin = None


class FleetSession:
    """One logical tenant session fanned over the fleet. Thread-safe;
    futures resolve on the controller's worker pool."""

    def __init__(self, ctl: "FleetController", sid: str, *,
                 priority: float = 1.0, slo: str = "throughput",
                 allow_degraded: bool = False):
        self._ctl = ctl
        self.sid = sid
        self.weight = max(float(priority), 0.01)
        self.slo = slo if slo in ("latency", "throughput") \
            else "throughput"
        self.allow_degraded = bool(allow_degraded)
        self._mu = threading.Lock()
        self._inflight = 0
        self._qseq = 0
        self.closed = False

    def submit(self, fn: Callable, *, key: Optional[str] = None) -> Future:
        """Queue a thunk on the fleet; returns a Future. ``key`` is the
        routing key (defaults to a digest of the pickled thunk, so a
        verbatim-repeated template routes to the same gang and its warm
        result cache). Raises typed rejections synchronously when the
        session is closed, over quota, or no gang is serviceable."""
        return self._ctl._submit(self, fn, key)

    def run(self, fn: Callable, *, key: Optional[str] = None,
            timeout: Optional[float] = None):
        return self.submit(fn, key=key).result(timeout=timeout)

    def close(self) -> None:
        self.closed = True
        self._ctl._close_session(self)


class FleetController:
    """Single controller fronting N gang processes."""

    def __init__(self, gangs: Optional[int] = None, *,
                 gang_env: Optional[Dict[int, Dict[str, str]]] = None):
        self.n_gangs = int(gangs if gangs is not None
                           else config.fleet_gangs)
        if self.n_gangs < 1:
            raise ValueError("fleet needs at least one gang")
        self._gang_env = gang_env or {}
        self._mu = threading.Lock()
        self._gangs: Dict[str, _GangState] = {}
        self._ring = _Ring()
        self._sessions: Dict[str, FleetSession] = {}
        self._pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="fleet-rt")
        self._stop_ev = threading.Event()
        self._scrape_thread: Optional[threading.Thread] = None
        self._listener: Optional[socket.socket] = None
        self._tmpdir: Optional[str] = None
        self._c: Dict[str, int] = {}
        self._started = False
        self._next_idx = 0

    # -- lifecycle ---------------------------------------------------------

    def _spawn_gang(self, i: int) -> Tuple[_GangState, str]:
        gid = f"gang-{i}"
        ready = os.path.join(self._tmpdir, f"ready_{i}.json")
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env.update({
            "BODO_TPU_GANG_ID": gid,
            "BODO_TPU_FLEET_READY": ready,
            "PYTHONPATH": pkg_root + os.pathsep +
            env.get("PYTHONPATH", ""),
        })
        # CPU by default: N gangs sharing one host must not fight
        # over an accelerator unless the caller says so explicitly
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self._gang_env.get(i, {}))
        g = _GangState(gid)
        ef = open(os.path.join(self._tmpdir, f"stderr_{i}.log"), "wb")
        of = open(os.path.join(self._tmpdir, f"stdout_{i}.log"), "wb")
        g.proc = subprocess.Popen(
            [sys.executable, "-c",
             "from bodo_tpu.runtime.fleet import gang_main; "
             "gang_main()"],
            env=env, stdin=subprocess.PIPE, stdout=of, stderr=ef,
            cwd=pkg_root)
        g.stdin = g.proc.stdin
        return g, ready

    def _await_ready(self, g: _GangState, ready: str,
                     deadline: float) -> None:
        while not os.path.exists(ready):
            if g.proc.poll() is not None:
                raise RuntimeError(
                    f"fleet gang {g.gang_id} died during startup "
                    f"(rc={g.proc.returncode}); stderr: "
                    f"{self._tail(g.gang_id)}")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fleet gang {g.gang_id} not ready in time")
            time.sleep(0.05)
        with open(ready) as f:
            info = json.load(f)
        g.serve_addr = info["serve_addr"]
        g.telemetry_addr = info.get("telemetry_addr") or ""

    def start(self, *, timeout: float = 120.0) -> "FleetController":
        if self._started:
            return self
        self._tmpdir = tempfile.mkdtemp(prefix="bodo_tpu_fleet_")
        ready_paths = {}
        for i in range(self.n_gangs):
            g, ready = self._spawn_gang(i)
            self._gangs[g.gang_id] = g
            ready_paths[g.gang_id] = ready
        self._next_idx = self.n_gangs
        deadline = time.monotonic() + timeout
        for gid, ready in ready_paths.items():
            g = self._gangs[gid]
            try:
                self._await_ready(g, ready, deadline)
            except TimeoutError:
                self.stop()
                raise
            self._ring.add(gid)
        self._started = True
        self._stop_ev.clear()
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, daemon=True, name="fleet-scrape")
        self._scrape_thread.start()
        port = int(config.fleet_port)
        if port >= 0:
            self.listen(port)
        log(1, f"fleet controller up: {self.n_gangs} gangs "
               f"({', '.join(g.serve_addr for g in self._gangs.values())})")
        return self

    def add_gang(self, *, timeout: float = 120.0,
                 env: Optional[Dict[str, str]] = None) -> str:
        """Scale out: spawn one more gang and join it to the ring.
        Only ~1/N of the keyspace moves to it; moved keys peer-fetch
        their cache entries from the previous owner on first miss, so
        locality survives the join. Returns the new gang id."""
        if not self._started:
            raise RuntimeError("fleet is not running")
        with self._mu:
            i = self._next_idx
            self._next_idx += 1
        if env:
            self._gang_env[i] = dict(env)
        g, ready = self._spawn_gang(i)
        self._await_ready(g, ready, time.monotonic() + timeout)
        with self._mu:
            self._gangs[g.gang_id] = g
            self._ring.add(g.gang_id)
            self.n_gangs = len(self._ring.members())
        log(1, f"fleet: gang {g.gang_id} joined "
               f"({g.serve_addr}); ring is now {self._ring.members()}")
        return g.gang_id

    def _tail(self, gid: str, n: int = 2000) -> str:
        try:
            i = gid.rsplit("-", 1)[1]
            with open(os.path.join(self._tmpdir, f"stderr_{i}.log"),
                      "rb") as f:
                return f.read()[-n:].decode("utf-8", "replace")
        except Exception:  # noqa: BLE001
            return ""

    def stop(self, *, timeout: float = 10.0) -> None:
        self._stop_ev.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        for g in self._gangs.values():
            if g.proc is None or g.proc.poll() is not None:
                continue
            try:
                with _connect(g.serve_addr, timeout=2.0) as s:
                    _send_json(s, {"op": "shutdown"})
                    _recv_json(s)
            except Exception:  # noqa: BLE001
                pass
        deadline = time.monotonic() + timeout
        for g in self._gangs.values():
            if g.proc is None:
                continue
            try:
                if g.stdin is not None:
                    g.stdin.close()
            except OSError:
                pass
            try:
                g.proc.wait(timeout=max(deadline - time.monotonic(),
                                        0.1))
            except subprocess.TimeoutExpired:
                g.proc.kill()
                g.proc.wait(timeout=5.0)
        self._pool.shutdown(wait=False)
        self._started = False

    # -- scraping / admission ---------------------------------------------

    def _scrape_one(self, g: _GangState) -> None:
        if not g.telemetry_addr:
            return
        try:
            with urllib.request.urlopen(
                    f"http://{g.telemetry_addr}/healthz",
                    timeout=3.0) as r:
                health = json.loads(r.read().decode("utf-8"))
            with urllib.request.urlopen(
                    f"http://{g.telemetry_addr}/metrics",
                    timeout=3.0) as r:
                met = r.read().decode("utf-8")
        except Exception:  # noqa: BLE001
            with self._mu:
                g.fail_scrapes += 1
                self._c["scrape_failures"] = \
                    self._c.get("scrape_failures", 0) + 1
                if g.fail_scrapes >= max(int(config.fleet_dead_scrapes),
                                         1) and g.state != "dead":
                    self._mark_dead_locked(
                        g, f"{g.fail_scrapes} consecutive scrape "
                           f"failures")
            return
        sig = signals_from_health(health).merged(
            signals_from_metrics(met))
        m = _PEER_HITS_RE.search(met)
        if m is not None:
            try:
                g.peer_hits = int(float(m.group(1)))
            except ValueError:
                pass
        d = g.admission.decide(sig, None)
        with self._mu:
            g.fail_scrapes = 0
            if g.state == "dead":
                return  # eviction is one-way; restart is out of scope
            state = {"admit": "ok", "shed": "shed",
                     "degrade": "degraded",
                     "backoff": "backoff"}.get(d.action, "ok")
            if state != g.state:
                log(1, f"fleet: gang {g.gang_id} {g.state} -> {state}"
                       f" ({d.reason})")
            g.state = state
            g.reason = d.reason
            g.retry_after_s = d.retry_after_s
            cap = sig.gang_capacity_frac
            cap = 1.0 if cap is None else min(max(float(cap), 0.0), 1.0)
            if cap != g.capacity_frac:
                log(1, f"fleet: gang {g.gang_id} capacity "
                       f"{g.capacity_frac:.2f} -> {cap:.2f} "
                       f"(elastic epoch {sig.elastic_epoch})")
            g.capacity_frac = cap

    def _mark_dead_locked(self, g: _GangState, why: str) -> None:
        g.state = "dead"
        g.reason = why
        self._ring.remove(g.gang_id)
        self._c["gangs_evicted"] = self._c.get("gangs_evicted", 0) + 1
        log(0, f"fleet: gang {g.gang_id} declared dead ({why}); "
               f"evicted from ring — keyspace reroutes to "
               f"{self._ring.members()}")

    def _scrape_loop(self) -> None:
        while not self._stop_ev.is_set():
            for g in list(self._gangs.values()):
                if self._stop_ev.is_set():
                    return
                if g.state == "dead":
                    continue
                if g.proc is not None and g.proc.poll() is not None:
                    with self._mu:
                        if g.state != "dead":
                            self._mark_dead_locked(
                                g, f"process exited "
                                   f"rc={g.proc.returncode}")
                    continue
                self._scrape_one(g)
            self._push_metrics()
            self._stop_ev.wait(max(float(config.fleet_scrape_s), 0.05))

    def _push_metrics(self) -> None:
        try:
            from bodo_tpu.utils import metrics
            gs = metrics.gauge("bodo_tpu_fleet_gangs",
                               "fleet gangs by controller-visible "
                               "state", ("state",))
            by: Dict[str, int] = {}
            with self._mu:
                for g in self._gangs.values():
                    by[g.state] = by.get(g.state, 0) + 1
                c = dict(self._c)
                c["peer_hits"] = sum(g.peer_hits
                                     for g in self._gangs.values())
                n_sessions = len(self._sessions)
            for st in ("ok", "shed", "degraded", "backoff", "dead"):
                gs.labels(state=st).set(by.get(st, 0))
            metrics.gauge("bodo_tpu_fleet_sessions",
                          "open fleet sessions").set(n_sessions)
            for name, help_ in (
                    ("rerouted", "submits routed around an "
                                 "unhealthy/dead gang"),
                    ("scrape_failures", "failed gang scrapes"),
                    ("gangs_evicted", "gangs evicted from the ring"),
                    ("invalidations_broadcast",
                     "fleet-wide cache invalidation broadcasts"),
                    ("quota_rejections",
                     "session-quota typed rejections"),
                    ("peer_hits", "peered cache fills observed in "
                                  "submit responses")):
                metrics.gauge(f"bodo_tpu_fleet_{name}_total",
                              help_).set(c.get(name, 0))
        except Exception:  # noqa: BLE001 - metrics must never hurt
            pass

    # -- sessions / submission --------------------------------------------

    def session(self, session_id: Optional[str] = None, *,
                priority: float = 1.0, slo: str = "throughput",
                allow_degraded: bool = False) -> FleetSession:
        with self._mu:
            sid = session_id or f"fs{len(self._sessions) + 1}"
            s = self._sessions.get(sid)
            if s is None:
                s = FleetSession(self, sid, priority=priority, slo=slo,
                                 allow_degraded=allow_degraded)
                self._sessions[sid] = s
            else:
                s.weight = max(float(priority), 0.01)
                s.slo = slo if slo in ("latency", "throughput") \
                    else "throughput"
                s.allow_degraded = bool(allow_degraded)
                s.closed = False
            return s

    def _close_session(self, s: FleetSession) -> None:
        for g in self._gangs.values():
            if g.state == "dead" or not g.serve_addr:
                continue
            try:
                with _connect(g.serve_addr, timeout=3.0) as sock:
                    _send_json(sock, {"op": "close", "sid": s.sid})
                    _recv_json(sock)
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    def _routing_key(fn: Callable, key: Optional[str]) -> str:
        if key:
            return str(key)
        try:
            import cloudpickle
            return hashlib.sha256(
                cloudpickle.dumps(fn)).hexdigest()[:24]
        except Exception:  # noqa: BLE001 - unroutable ≠ unservable
            return f"anon-{id(fn)}"

    def _route(self, rkey: str) -> _GangState:
        """Owner gang for a routing key, walking ring successors around
        non-ok gangs. All-bad ⇒ the healthiest gang's typed rejection
        (with its retry hint) so clients back off instead of hanging."""
        with self._mu:
            order = self._ring.successors(rkey)
            cands = [self._gangs[gid] for gid in order
                     if gid in self._gangs]
            if not cands:
                raise Overloaded(
                    "fleet has no live gangs (all evicted)",
                    retry_after_s=max(
                        float(config.serve_retry_after_s), 0.25) * 4,
                    reason="no_gangs")
            ok = [(i, g) for i, g in enumerate(cands)
                  if g.state == "ok"]
            if ok:
                # affinity first — but when the ring owner is a shrunk
                # (elastic) gang and a full-capacity gang is also ok,
                # spill the key to the full gang: the shrunk gang keeps
                # its warm keys only while no better host exists
                i, g = ok[0]
                if g.capacity_frac < 1.0:
                    full = [(j, h) for j, h in ok
                            if h.capacity_frac >= 1.0]
                    if full:
                        i, g = full[0]
                        self._c["capacity_rerouted"] = \
                            self._c.get("capacity_rerouted", 0) + 1
                if i > 0:
                    self._c["rerouted"] = \
                        self._c.get("rerouted", 0) + 1
                return g
            # no healthy gang: surface the least-bad state typed
            sev = {"backoff": 0, "shed": 1, "degraded": 2, "dead": 3}
            best = min(cands, key=lambda g: sev.get(g.state, 3))
            exc_cls = {"shed": Overloaded, "backoff": BackOff,
                       "degraded": Degraded}.get(best.state, Overloaded)
            raise exc_cls(
                f"no serviceable gang: best is {best.gang_id} "
                f"({best.state}: {best.reason})",
                retry_after_s=best.retry_after_s
                or max(float(config.fleet_scrape_s), 0.25) * 2,
                reason=f"fleet_{best.state}")

    def _capacity_frac(self) -> float:
        """Mean surviving-rank fraction across live gangs (1.0 for an
        unshrunk fleet; dead gangs don't count — the ring already
        rerouted their keyspace)."""
        with self._mu:
            caps = [g.capacity_frac for g in self._gangs.values()
                    if g.state != "dead"]
        if not caps:
            return 1.0
        return min(max(sum(caps) / len(caps), 0.0), 1.0)

    def _submit(self, s: FleetSession, fn: Callable,
                key: Optional[str]) -> Future:
        if s.closed:
            raise Overloaded(f"fleet session {s.sid!r} is closed",
                             reason="session_closed")
        # a shrunk fleet admits proportionally less: quota scales by the
        # mean surviving-rank fraction of live gangs, so an elastic
        # N->N-1 shrink sheds load instead of queueing it onto fewer
        # ranks (capacity restores to 1.0 once the gang grows back)
        cap = self._capacity_frac()
        quota = max(int(round(int(config.fleet_session_quota) * cap)), 1)
        with s._mu:
            if s._inflight >= quota:
                self._c["quota_rejections"] = \
                    self._c.get("quota_rejections", 0) + 1
                raise Overloaded(
                    f"session {s.sid!r} has {s._inflight} queries in "
                    f"flight (quota {quota})",
                    retry_after_s=max(
                        float(config.serve_retry_after_s), 0.25),
                    reason="session_quota")
            s._inflight += 1
            s._qseq += 1
            qid = f"{s.sid}-q{s._qseq}"
        rkey = self._routing_key(fn, key)
        fut = self._pool.submit(self._roundtrip, s, fn, rkey, qid)

        def _done(_):
            with s._mu:
                s._inflight -= 1
        fut.add_done_callback(_done)
        return fut

    def _roundtrip(self, s: FleetSession, fn: Callable, rkey: str,
                   qid: str):
        """Blocking submit exchange with the owner gang (runs on the
        controller pool). Mid-stream gang death becomes a typed
        QueryFailed AND an immediate eviction — queued work re-routes,
        the in-flight query is NOT silently retried."""
        g = self._route(rkey)
        with self._mu:
            peer = self._ring.prev_owner(rkey)
            peer_addr = None
            if peer is not None and peer != g.gang_id:
                pg = self._gangs.get(peer)
                if pg is not None and pg.state != "dead":
                    peer_addr = pg.serve_addr
        peering = bool(config.fleet_peering)
        try:
            sock = _connect(g.serve_addr, timeout=10.0)
        except OSError as e:
            # never reached the gang: routing again is safe (nothing
            # ran). Mark it and take the next ring successor.
            self._note_gang_failure(g, f"connect failed: {e}")
            g2 = self._route(rkey)
            if g2.gang_id == g.gang_id:
                raise QueryFailed(s.sid, qid, e) from None
            return self._roundtrip_on(g2, s, fn, rkey, qid, peer_addr
                                      if peering else None)
        with sock:
            return self._exchange(sock, g, s, fn, qid,
                                  peer_addr if peering else None)

    def _roundtrip_on(self, g: _GangState, s: FleetSession,
                      fn: Callable, rkey: str, qid: str,
                      peer_addr: Optional[str]):
        try:
            sock = _connect(g.serve_addr, timeout=10.0)
        except OSError as e:
            self._note_gang_failure(g, f"connect failed: {e}")
            raise QueryFailed(s.sid, qid, e) from None
        with sock:
            return self._exchange(sock, g, s, fn, qid, peer_addr)

    def _exchange(self, sock: socket.socket, g: _GangState,
                  s: FleetSession, fn: Callable, qid: str,
                  peer_addr: Optional[str]):
        sock.settimeout(600.0)
        try:
            _send_json(sock, {"op": "submit", "sid": s.sid, "qid": qid,
                              "weight": s.weight, "slo": s.slo,
                              "allow_degraded": s.allow_degraded,
                              "peer": peer_addr})
            _send_pickle(sock, fn)
            head = _recv_json(sock)
        except (ProtocolError, OSError) as e:
            self._note_gang_failure(g, f"died before ack: {e}")
            self._count_req(g, "failed")
            raise QueryFailed(s.sid, qid, ProtocolError(
                f"gang {g.gang_id} failed before acknowledging: "
                f"{e}")) from None
        if head.get("ev") != "ack":
            self._count_req(g, "rejected")
            raise _exc_from_wire(head, sid=s.sid, qid=qid)
        try:
            res = _recv_json(sock)
        except (ProtocolError, OSError) as e:
            # mid-stream death: the query was in flight on that gang —
            # typed failure to THIS client, eviction + reroute for
            # everything queued behind it
            self._note_gang_failure(
                g, f"died mid-stream on {qid}: {e}", force_dead=True)
            self._count_req(g, "died_midstream")
            raise QueryFailed(s.sid, qid, ProtocolError(
                f"gang {g.gang_id} died mid-stream (after ack, before "
                f"result)")) from None
        self._broadcast_invalidations(g, res.get("invalidated") or [])
        if not res.get("ok"):
            self._count_req(g, "failed")
            raise _exc_from_wire(res, sid=s.sid, qid=qid)
        try:
            out = _recv_pickle(sock)
        except (ProtocolError, OSError) as e:
            self._note_gang_failure(
                g, f"died sending payload for {qid}: {e}",
                force_dead=True)
            self._count_req(g, "died_midstream")
            raise QueryFailed(s.sid, qid, ProtocolError(
                f"gang {g.gang_id} died sending the result payload"))\
                from None
        self._count_req(g, "ok")
        return out

    def _count_req(self, g: _GangState, outcome: str) -> None:
        with self._mu:
            k = f"req_{outcome}"
            self._c[k] = self._c.get(k, 0) + 1
        try:
            from bodo_tpu.utils import metrics
            metrics.counter("bodo_tpu_fleet_requests_total",
                            "fleet submits by gang and outcome",
                            ("gang", "outcome")).labels(
                gang=g.gang_id, outcome=outcome).inc()
        except Exception:  # noqa: BLE001
            pass

    def _note_gang_failure(self, g: _GangState, why: str,
                           force_dead: bool = False) -> None:
        with self._mu:
            if g.state == "dead":
                return
            dead = force_dead or (g.proc is not None
                                  and g.proc.poll() is not None)
            if dead:
                self._mark_dead_locked(g, why)
            else:
                g.state = "backoff"
                g.reason = why

    def _broadcast_invalidations(self, origin: _GangState,
                                 paths: list) -> None:
        """Fan a gang's mutation-invalidated source paths to every
        OTHER gang (the origin already dropped its stale entry and
        recorded the fresh one — hitting it again would drop the fresh
        entry)."""
        if not paths:
            return
        with self._mu:
            self._c["invalidations_broadcast"] = \
                self._c.get("invalidations_broadcast", 0) + 1
            targets = [g for g in self._gangs.values()
                       if g.gang_id != origin.gang_id
                       and g.state != "dead" and g.serve_addr]
        for g in targets:
            try:
                with _connect(g.serve_addr, timeout=5.0) as sock:
                    _send_json(sock, {"op": "invalidate",
                                      "paths": list(paths)})
                    _recv_json(sock)
            except Exception as e:  # noqa: BLE001
                # an unreachable gang is (or is about to be) evicted;
                # its cache dies with the process, so staleness cannot
                # leak through this miss
                log(2, f"fleet: invalidate to {g.gang_id} failed: "
                       f"{type(e).__name__}: {e}")

    # -- introspection -----------------------------------------------------

    def gang_stats(self, gang_id: str) -> Optional[dict]:
        """The gang's own scheduler/result-cache counters over the
        wire (None when unreachable)."""
        g = self._gangs.get(gang_id)
        if g is None or not g.serve_addr:
            return None
        try:
            with _connect(g.serve_addr, timeout=5.0) as sock:
                _send_json(sock, {"op": "stats"})
                return _recv_json(sock)
        except Exception:  # noqa: BLE001
            return None

    def stats(self) -> dict:
        with self._mu:
            peer_hits = sum(g.peer_hits for g in self._gangs.values())
            gangs = {
                g.gang_id: {
                    "state": g.state, "reason": g.reason,
                    "addr": g.serve_addr,
                    "telemetry": g.telemetry_addr,
                    "pid": g.proc.pid if g.proc is not None else None,
                    "capacity_frac": g.capacity_frac,
                } for g in self._gangs.values()}
            out = {
                "gangs": gangs,
                "ring_members": self._ring.members(),
                "sessions": len(self._sessions),
                "rerouted": self._c.get("rerouted", 0),
                "capacity_rerouted":
                    self._c.get("capacity_rerouted", 0),
                "scrape_failures": self._c.get("scrape_failures", 0),
                "gangs_evicted": self._c.get("gangs_evicted", 0),
                "invalidations_broadcast":
                    self._c.get("invalidations_broadcast", 0),
                "quota_rejections": self._c.get("quota_rejections", 0),
                "peer_hits": peer_hits,
                "requests": {k[4:]: v for k, v in self._c.items()
                             if k.startswith("req_")},
            }
        return out

    # -- optional client listener (BODO_TPU_FLEET_PORT) --------------------

    def listen(self, port: int) -> str:
        """Serve the wire protocol to REMOTE clients: open/submit/
        close/stats against this controller (connect() is the client).
        Returns the bound address."""
        if self._listener is not None:
            return self._listen_addr
        srv = socket.create_server(("127.0.0.1", max(port, 0)))
        srv.listen(32)
        self._listener = srv
        self._listen_addr = f"127.0.0.1:{srv.getsockname()[1]}"
        threading.Thread(target=self._listen_loop, daemon=True,
                         name="fleet-listen").start()
        log(1, f"fleet controller listening on {self._listen_addr}")
        return self._listen_addr

    def _listen_loop(self) -> None:
        while not self._stop_ev.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._client_conn, args=(conn,),
                             daemon=True).start()

    def _client_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                req = _recv_json(conn)
                op = req.get("op")
                if op == "ping":
                    _send_json(conn, {"ok": True, "role": "controller",
                                      "gangs": self.n_gangs})
                elif op == "open":
                    self.session(
                        req.get("sid"),
                        priority=float(req.get("weight", 1.0)),
                        slo=req.get("slo", "throughput"),
                        allow_degraded=bool(
                            req.get("allow_degraded", False)))
                    _send_json(conn, {"ok": True})
                elif op == "close":
                    s = self._sessions.get(req.get("sid") or "")
                    if s is not None:
                        s.close()
                    _send_json(conn, {"ok": True})
                elif op == "stats":
                    _send_json(conn, {"ok": True,
                                      "fleet": self.stats()})
                elif op == "submit":
                    fn = _recv_pickle(conn)
                    s = self.session(req.get("sid") or "remote")
                    try:
                        fut = s.submit(fn, key=req.get("key"))
                    except (ServeRejection, QueryFailed) as e:
                        _send_json(conn, _exc_to_wire(e))
                        return
                    _send_json(conn, {"ev": "ack",
                                      "qid": req.get("qid")})
                    try:
                        out = fut.result(timeout=600.0)
                    except (ServeRejection, QueryFailed) as e:
                        _send_json(conn, dict(_exc_to_wire(e),
                                              ev="result"))
                        return
                    except Exception as e:  # noqa: BLE001
                        _send_json(conn, dict(_exc_to_wire(e),
                                              ev="result"))
                        return
                    _send_json(conn, {"ev": "result", "ok": True})
                    _send_pickle(conn, out)
                else:
                    _send_json(conn, {"ok": False,
                                      "etype": "ProtocolError",
                                      "msg": f"unknown op {op!r}"})
        except Exception as e:  # noqa: BLE001 - one bad client only
            log(2, f"fleet listener: connection error: "
                   f"{type(e).__name__}: {e}")


class RemoteFleet:
    """Client of a controller's listener (``fleet.connect(addr)``)."""

    def __init__(self, addr: str):
        self.addr = addr

    def ping(self) -> dict:
        with _connect(self.addr, timeout=5.0) as s:
            _send_json(s, {"op": "ping"})
            return _recv_json(s)

    def open(self, sid: str, *, priority: float = 1.0,
             slo: str = "throughput",
             allow_degraded: bool = False) -> None:
        with _connect(self.addr, timeout=5.0) as s:
            _send_json(s, {"op": "open", "sid": sid, "weight": priority,
                           "slo": slo, "allow_degraded": allow_degraded})
            _recv_json(s)

    def run(self, fn: Callable, *, sid: str = "remote",
            key: Optional[str] = None, timeout: float = 600.0):
        with _connect(self.addr, timeout=timeout) as s:
            s.settimeout(timeout)
            _send_json(s, {"op": "submit", "sid": sid, "key": key})
            _send_pickle(s, fn)
            head = _recv_json(s)
            if head.get("ev") != "ack":
                raise _exc_from_wire(head, sid=sid)
            res = _recv_json(s)
            if not res.get("ok"):
                raise _exc_from_wire(res, sid=sid)
            return _recv_pickle(s)

    def close(self, sid: str) -> None:
        with _connect(self.addr, timeout=5.0) as s:
            _send_json(s, {"op": "close", "sid": sid})
            _recv_json(s)

    def stats(self) -> dict:
        with _connect(self.addr, timeout=5.0) as s:
            _send_json(s, {"op": "stats"})
            return _recv_json(s).get("fleet", {})


# ---------------------------------------------------------------------------
# module singleton + façade
# ---------------------------------------------------------------------------

_controller: Optional[FleetController] = None
_ctl_mu = threading.Lock()


def start(gangs: Optional[int] = None, *,
          gang_env: Optional[Dict[int, Dict[str, str]]] = None,
          timeout: float = 120.0) -> FleetController:
    """Bring a fleet up (idempotent while one is running)."""
    global _controller
    with _ctl_mu:
        if _controller is not None and _controller._started:
            return _controller
        _controller = FleetController(gangs, gang_env=gang_env)
    return _controller.start(timeout=timeout)


def stop() -> None:
    global _controller
    with _ctl_mu:
        ctl, _controller = _controller, None
    if ctl is not None:
        ctl.stop()


def controller() -> Optional[FleetController]:
    return _controller


def controller_stats() -> Optional[dict]:
    """Telemetry hook: the live controller's fleet block (None when no
    controller is running in this process)."""
    ctl = _controller
    if ctl is None or not ctl._started:
        return None
    try:
        return ctl.stats()
    except Exception:  # noqa: BLE001
        return None


def reconfigure() -> None:
    """config.set_config hook for fleet_* knobs: wake the scrape loop
    so cadence/thresholds re-read config immediately."""
    # the scrape loop re-reads config.fleet_* each tick and nothing
    # else is cached, so new values take effect within one cadence
    _ = _controller


def connect(addr: str) -> RemoteFleet:
    """Client handle on a controller's listener address."""
    return RemoteFleet(addr)
