"""Distributed training over dataframe features (torch_train analogue).

One jit-compiled epoch: parameters replicated, batches row-sharded over
the mesh; jax.grad + optax; the cross-shard gradient reduction is the
sharding-induced psum (the reference's DDP allreduce,
bodo/ai/train.py:42 _init_process_group → here: the mesh already exists).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.ml._data import to_device_xy


def train(loss_fn: Callable, params, df, feature_cols: Sequence[str],
          label_col: str, *, epochs: int = 5, batch_size: int = 1024,
          learning_rate: float = 1e-3, optimizer=None, seed: int = 0,
          verbose: bool = False):
    """Train `params` with `loss_fn(params, X_batch, y_batch) -> scalar`.

    df: pandas or lazy frame; features/labels become row-sharded device
    arrays. Returns (trained params, list of per-epoch mean losses).
    """
    import optax

    from bodo_tpu.ml._data import _is_lazy, table_to_device_xy

    if _is_lazy(df):
        # worker/device-resident path: the executed Table's columns cast
        # + realign on device — no to_pandas() gather (reference:
        # bodo/ai/train.py:104 feeds training from worker-resident data)
        t = df._execute()
        Xd, yd, mask, n = table_to_device_xy(t, list(feature_cols),
                                             label_col)
    else:
        X = df[list(feature_cols)].to_numpy(dtype=np.float64)
        y = df[label_col].to_numpy(dtype=np.float64)
        Xd, yd, mask, n = to_device_xy(X, y)
    opt = optimizer or optax.adam(learning_rate)
    opt_state = opt.init(params)
    # permute REAL rows only — padding rows must never enter a batch
    # (a scalar-returning loss_fn cannot be masked after the fact)
    batch_size = min(batch_size, max(n, 1))
    n_batches = max(1, n // batch_size)

    # one jit per train() call, dies with the closure — nothing to
    # register  # shardcheck: ignore[unregistered-jit]
    @jax.jit
    def epoch(params, opt_state, perm):
        def step(carry, idx):
            params, opt_state = carry
            rows = jax.lax.dynamic_slice_in_dim(perm, idx * batch_size,
                                                batch_size)
            xb = Xd[rows]
            yb = yd[rows]
            mb = mask[rows].astype(xb.dtype)

            def masked_loss(p):
                per = loss_fn(p, xb, yb)
                # loss_fn may return per-example or scalar loss
                per = jnp.asarray(per)
                if per.ndim == 0:
                    return per
                return jnp.sum(per * mb) / jnp.maximum(jnp.sum(mb), 1)

            loss, g = jax.value_and_grad(masked_loss)(params)
            updates, opt_state = opt.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), jnp.arange(n_batches))
        return params, opt_state, jnp.mean(losses)

    r = np.random.default_rng(seed)
    history = []
    for e in range(epochs):
        perm = jnp.asarray(r.permutation(n))
        params, opt_state, loss = epoch(params, opt_state, perm)
        history.append(float(loss))
        if verbose:  # pragma: no cover
            print(f"epoch {e}: loss={history[-1]:.6f}")
    return params, history
