"""Series.ai accessor (reference bodo/ai/series.py:12-42 —
tokenize/llm_generate/embed; accessor registered at
bodo/pandas/series.py:729).

Backends are pluggable callables (str -> result); batched over the
column's host dictionary so each distinct string is processed once —
the dict-encoding win applies to model calls too.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np
import pandas as pd


class AiAccessor:
    def __init__(self, series):
        self._s = series

    def _distinct_apply(self, fn: Callable, name: str):
        """Apply fn once per distinct string, broadcast via codes."""
        from bodo_tpu.table import dtypes as dt
        s = self._s
        if s._dtype is not dt.STRING:
            raise TypeError(f"Series.ai.{name} requires a string column")
        pds = s.to_pandas()
        codes, uniques = pd.factorize(pds, use_na_sentinel=True)
        results = [fn(u) for u in uniques]
        out = [results[c] if c >= 0 else None for c in codes]
        return pd.Series(out, name=s.name)

    def tokenize(self, tokenizer: Optional[Callable] = None):
        """tokenizer: str -> list[int]; defaults to a whitespace/byte
        tokenizer when none is given (remote tokenizers need a backend)."""
        fn = tokenizer or (lambda s: list(s.encode("utf-8")))
        return self._distinct_apply(fn, "tokenize")

    def embed(self, model: Optional[Callable] = None, dim: int = 64):
        """model: str -> np.ndarray; default is a deterministic hashed
        bag-of-bytes embedding (offline-friendly stand-in)."""
        if model is None:
            def model(s: str, _dim=dim):
                v = np.zeros(_dim)
                for i, b in enumerate(s.encode("utf-8")):
                    v[(b * 31 + i) % _dim] += 1.0
                n = np.linalg.norm(v)
                return v / n if n else v
        return self._distinct_apply(model, "embed")

    def llm_generate(self, generate: Callable = None, **kwargs):
        """generate: str -> str. No default — generation requires a local
        model backend (zero-egress environments cannot call endpoints)."""
        if generate is None:
            raise ValueError(
                "Series.ai.llm_generate requires a `generate` callable "
                "backend (remote endpoints are unavailable)")
        return self._distinct_apply(lambda s: generate(s, **kwargs),
                                    "llm_generate")
