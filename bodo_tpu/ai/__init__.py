"""AI utilities (reference bodo/ai/: torch_train + Series.ai accessor).

The reference feeds distributed dataframes into torch DDP
(bodo/ai/train.py:104 torch_train, prepare_model:144). The TPU-native
equivalent keeps training on the same mesh the dataframes live on:
`train()` runs a jit-compiled optax loop over row-sharded features with
replicated parameters — XLA inserts the gradient psum (the DDP allreduce
analogue) from the shardings.

`Series.ai` (tokenize/embed/llm_generate, reference bodo/ai/series.py)
takes pluggable callables: the reference calls remote endpoints, which a
zero-egress environment replaces with user-provided local backends.
"""

from bodo_tpu.ai.train import train
from bodo_tpu.ai.series import AiAccessor

__all__ = ["train", "AiAccessor"]
