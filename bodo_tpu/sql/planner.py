"""SQL AST → logical plan.

Replaces the reference's Calcite planner + plan conversion
(BodoSQL/bodosql/plan_conversion.py java_plan_to_python_plan and the
RelationalAlgebraGenerator pipeline) with a direct lowering onto the same
LazyPlan nodes the dataframe frontend uses (bodo_tpu/plan/logical.py) —
one engine, two frontends, like the reference's C++-backend path
(BodoSQL/bodosql/context.py:504 execute_cpp_backend).

Name resolution uses globally unique flat column names per table
reference (t<N>__col), so joins never collide and suffix logic is
unnecessary. Subqueries lower to joins: IN/EXISTS → semi join (inner join
against a Distinct subplan), NOT IN/NOT EXISTS → anti join (left join +
IS NULL filter), correlated predicates decorrelate through equality
conjuncts, and correlated scalar aggregate subqueries become grouped
aggregates joined on the correlation keys (the standard Kim/Dayal
unnesting the reference gets from Calcite rules).
"""

from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Tuple

import numpy as np

from bodo_tpu.plan import logical as L
from bodo_tpu.plan.expr import (BinOp, Cast, ColRef, DictMap, DtField, Expr,
                                IsIn, Lit, StrHostFn, StrPredicate, UnOp,
                                Where, infer_dtype)
from bodo_tpu.sql import parser as P
from bodo_tpu.table import dtypes as dt

_AGG_MAP = {"sum": "sumnull", "avg": "mean", "min": "min", "max": "max",
            "count": "count", "stddev": "std", "variance": "var",
            "var_samp": "var", "stddev_samp": "std",
            "var_pop": "var0", "stddev_pop": "std0",
            "median": "median", "mode": "mode",
            "skew": "skew", "kurtosis": "kurt"}


class Scope:
    """Column name resolution: (qualifier, col) → flat plan column."""

    def __init__(self):
        self.by_qual: Dict[Tuple[str, str], str] = {}
        self.by_col: Dict[str, List[str]] = {}

    def add(self, qual: str, col: str, flat: str):
        self.by_qual[(qual.lower(), col.lower())] = flat
        self.by_col.setdefault(col.lower(), []).append(flat)

    def resolve(self, col: str, qual: Optional[str]) -> Optional[str]:
        if qual is not None:
            return self.by_qual.get((qual.lower(), col.lower()))
        hits = list(dict.fromkeys(self.by_col.get(col.lower(), [])))
        if len(hits) > 1:
            raise ValueError(f"ambiguous column {col}")
        return hits[0] if hits else None

    def merged(self, other: "Scope") -> "Scope":
        s = Scope()
        s.by_qual = {**self.by_qual, **other.by_qual}
        for k, v in self.by_col.items():
            s.by_col.setdefault(k, []).extend(v)
        for k, v in other.by_col.items():
            s.by_col.setdefault(k, []).extend(v)
        return s


class Planner:
    def __init__(self, catalog: Dict[str, L.Node]):
        self.catalog = {k.lower(): v for k, v in catalog.items()}
        self.counter = [0]

    def _fresh(self, base: str = "t") -> str:
        self.counter[0] += 1
        return f"{base}{self.counter[0]}"

    # ------------------------------------------------------------------
    def plan(self, sel) -> Tuple[L.Node, List[str]]:
        """Returns (plan, output column names)."""
        if isinstance(sel, P.UnionSel):
            return self._plan_union(sel)
        catalog = dict(self.catalog)
        for name, cte in sel.ctes:
            node, names = self.plan(cte)
            catalog[name.lower()] = L.Projection(
                node, [(n, ColRef(n)) for n in names])
        saved = self.catalog
        self.catalog = catalog
        try:
            return self._plan_core(sel, outer=None)
        finally:
            self.catalog = saved

    def _plan_union(self, u: "P.UnionSel") -> Tuple[L.Node, List[str]]:
        parts = [self.plan(s) for s in u.selects]
        names = parts[0][1]
        aligned = []
        for node, nm in parts:
            if len(nm) != len(names):
                raise ValueError("UNION arms have different column counts")
            aligned.append(L.Projection(
                node, [(names[i], ColRef(nm[i])) for i in range(len(names))]))
        # left-associative fold so mixed UNION / UNION ALL dedups correctly
        out: L.Node = aligned[0]
        for is_all, arm in zip(u.alls, aligned[1:]):
            out = L.Union([out, arm])
            if not is_all:
                out = L.Distinct(out, names)
        # trailing ORDER BY / LIMIT apply to the whole union; keys resolve
        # against the output columns (names or 1-based positions)
        if u.order_by:
            keys, asc = [], []
            for e, a in u.order_by:
                if isinstance(e, P.Num) and isinstance(e.value, int):
                    keys.append(names[e.value - 1])
                elif isinstance(e, P.Col) and e.qualifier is None and \
                        e.name in names:
                    keys.append(e.name)
                else:
                    raise NotImplementedError(
                        "UNION ORDER BY must reference output columns")
                asc.append(a)
            out = L.Sort(out, keys, asc)
        if u.limit is not None:
            out = L.Limit(out, u.limit)
        return out, names

    # ------------------------------------------------------------------
    def _from(self, item, outer: Optional[Scope]) -> Tuple[L.Node, Scope]:
        if isinstance(item, P.TableRef):
            base = self.catalog.get(item.name.lower())
            if base is None:
                raise ValueError(f"unknown table {item.name}")
            alias = (item.alias or item.name)
            tag = self._fresh()
            exprs = [(f"{tag}__{c}", ColRef(c)) for c in base.schema]
            plan = L.Projection(base, exprs)
            scope = Scope()
            for c in base.schema:
                scope.add(alias, c, f"{tag}__{c}")
            return plan, scope
        if isinstance(item, P.SubSelect):
            # plan() also routes UNION subselects
            node, names = self.plan(item.select)
            tag = self._fresh()
            exprs = [(f"{tag}__{c}", ColRef(c)) for c in names]
            plan = L.Projection(node, exprs)
            scope = Scope()
            for c in names:
                scope.add(item.alias, c, f"{tag}__{c}")
            return plan, scope
        if isinstance(item, P.JoinItem):
            lp, ls = self._from(item.left, outer)
            rp, rs = self._from(item.right, outer)
            scope = ls.merged(rs)
            if item.kind == "cross":
                return self._cross_join(lp, rp), scope
            if item.using is not None:
                # JOIN ... USING (a, b): equi keys by shared name; the
                # unqualified name resolves to the left side afterwards
                # (coalescing for FULL JOIN is not modeled — reject it)
                if item.kind == "outer":
                    raise NotImplementedError(
                        "FULL JOIN ... USING (coalesced key) — use ON")
                eq_l, eq_r, residual = [], [], None
                for c in item.using:
                    lc, rc = ls.resolve(c, None), rs.resolve(c, None)
                    if lc is None or rc is None:
                        raise ValueError(f"USING column {c} not on both "
                                         f"sides")
                    eq_l.append(lc)
                    eq_r.append(rc)
                    # the USING column coalesces; binding it to the
                    # preserved side's key is exact for inner/left/right
                    # (matched rows agree, unmatched preserved rows only
                    # have their own side's value)
                    scope.by_col[c.lower()] = \
                        [rc if item.kind == "right" else lc]
            else:
                eq_l, eq_r, residual = self._split_join_condition(
                    item.on, ls, rs, scope)
            how = item.kind
            if residual is not None and how == "outer":
                raise NotImplementedError(
                    "FULL JOIN with a non-equality ON condition")
            if residual is not None and how in ("left", "right"):
                # outer-join ON residuals restrict the null-padded side
                # BEFORE the join (a post-filter would turn preserved rows
                # into dropped ones — the Q13 pattern); residuals touching
                # BOTH sides fall through to the nested-loop join below
                from bodo_tpu.plan.expr import expr_columns
                cols = expr_columns(residual)
                inner_side = set(rs.by_qual.values()) if how == "left" \
                    else set(ls.by_qual.values())
                if cols <= inner_side:
                    if how == "left":
                        rp = L.Filter(rp, residual)
                    else:
                        lp = L.Filter(lp, residual)
                    residual = None
                elif eq_l:
                    raise NotImplementedError(
                        "outer-join ON mixing equality keys with a "
                        "residual touching the preserved side")
            if not eq_l:
                if residual is None:
                    raise NotImplementedError(
                        f"{how} join with no usable ON condition")
                # pure non-equi condition → tiled nested-loop /
                # interval join (reference:
                # bodo/libs/_nested_loop_join_impl.cpp, _interval_join)
                if how == "inner":
                    plan = L.NonEquiJoin(lp, rp, residual, "inner")
                elif how == "left":
                    plan = L.NonEquiJoin(lp, rp, residual, "left")
                elif how == "right":
                    plan = L.NonEquiJoin(rp, lp, residual, "left")
                else:
                    raise NotImplementedError(
                        "FULL JOIN with a pure non-equi condition")
                residual = None
            else:
                if how == "right":
                    plan = L.Join(rp, lp, eq_r, eq_l, "left", null_equal=False)
                else:
                    plan = L.Join(lp, rp, eq_l, eq_r, how, null_equal=False)
            if residual is not None:
                plan = L.Filter(plan, residual)
            return plan, scope
        raise TypeError(f"bad FROM item {item}")

    def _cross_join(self, lp: L.Node, rp: L.Node) -> L.Node:
        # constant-key join (small sides only — TPC-H cross joins are tiny)
        k = self._fresh("__cross")
        lp2 = L.Projection(lp, [(c, ColRef(c)) for c in lp.schema]
                           + [(k, Lit(1))])
        rp2 = L.Projection(rp, [(c, ColRef(c)) for c in rp.schema]
                           + [(k + "_r", Lit(1))])
        j = L.Join(lp2, rp2, [k], [k + "_r"], "inner", null_equal=False)
        keep = [c for c in j.schema if not c.startswith("__cross")]
        return L.Projection(j, [(c, ColRef(c)) for c in keep])

    def _split_join_condition(self, on, ls: Scope, rs: Scope, scope: Scope):
        """Equi-conjuncts spanning both sides become join keys; the rest
        becomes a post-join filter."""
        eq_l, eq_r, residual = [], [], []

        def visit(e):
            if isinstance(e, P.BinA) and e.op == "&":
                visit(e.left)
                visit(e.right)
                return
            if isinstance(e, P.BinA) and e.op == "==" and \
                    isinstance(e.left, P.Col) and isinstance(e.right, P.Col):
                lf = self._try_col(e.left, ls)
                rf = self._try_col(e.right, rs)
                if lf and rf:
                    eq_l.append(lf)
                    eq_r.append(rf)
                    return
                lf2 = self._try_col(e.right, ls)
                rf2 = self._try_col(e.left, rs)
                if lf2 and rf2:
                    eq_l.append(lf2)
                    eq_r.append(rf2)
                    return
            residual.append(e)

        visit(on)
        res_expr = None
        for r in residual:
            c = self._expr(r, scope, None, None)
            res_expr = c if res_expr is None else BinOp("&", res_expr, c)
        return eq_l, eq_r, res_expr

    def _try_col(self, c: P.Col, scope: Scope) -> Optional[str]:
        try:
            return scope.resolve(c.name, c.qualifier)
        except ValueError:
            return None

    # ------------------------------------------------------------------
    def _plan_core(self, sel: P.Select, outer: Optional[Scope]
                   ) -> Tuple[L.Node, List[str]]:
        for name, cte in sel.ctes:
            node, names = self.plan(cte)
            self.catalog[name.lower()] = L.Projection(
                node, [(n, ColRef(n)) for n in names])
        if sel.from_item is None:
            raise NotImplementedError("SELECT without FROM")
        plan, scope = self._plan_from_where(sel.from_item, sel.where, outer)
        # schema in scope for dtype-sensitive lowering (CAST to varchar)
        prev_schema = getattr(self, "_cur_schema", None)
        self._cur_schema = plan.schema

        # window-function extraction: each OVER (...) item is replaced by
        # a placeholder column now and planned as a RankWindow/AggWindow
        # node AFTER any GROUP BY aggregation (SQL evaluates window
        # functions over the grouped rows)
        windows = self._extract_windows(sel)

        # aggregate extraction
        aggs: List[Tuple[Expr, str, str]] = []   # (arg expr, op, temp name)

        def lower_aggs(e):
            """Replace agg Func nodes with placeholder Cols __agg<N>."""
            if isinstance(e, P.Func) and (e.star or e.name in _AGG_MAP or
                                          e.name in ("count", "listagg",
                                                     "string_agg")):
                if e.star:
                    op, arg = "size", None
                elif e.name == "count" and e.distinct:
                    op, arg = "nunique", e.args[0]
                elif e.name == "count":
                    op, arg = "count", e.args[0]
                elif e.name in ("listagg", "string_agg"):
                    sep = ","
                    if len(e.args) == 2:
                        if not isinstance(e.args[1], P.Str):
                            raise NotImplementedError(
                                "LISTAGG separator must be a string "
                                "literal")
                        sep = e.args[1].value
                    kind = "listaggd" if e.distinct else "listagg"
                    op, arg = f"{kind}:{sep}", e.args[0]
                else:
                    op, arg = _AGG_MAP[e.name], e.args[0]
                tmp = f"__agg{len(aggs)}"
                arg_expr = Lit(1) if arg is None else \
                    self._expr(arg, scope, None, None)
                aggs.append((arg_expr, op, tmp))
                return P.Col(tmp, qualifier="__agg")
            for f in getattr(e, "__dataclass_fields__", {}):
                v = getattr(e, f)
                if isinstance(v, tuple(_AST_TYPES)):
                    setattr(e, f, lower_aggs(v))
                elif isinstance(v, list):
                    setattr(e, f, [lower_aggs(x)
                                   if isinstance(x, tuple(_AST_TYPES)) else x
                                   for x in v])
                elif isinstance(v, tuple):
                    setattr(e, f, tuple(
                        lower_aggs(x) if isinstance(x, tuple(_AST_TYPES))
                        else x for x in v))
            return e

        has_aggs = sel.group_by or _contains_agg(sel.projections) or \
            (sel.having is not None)
        group_flat: List[str] = []
        if has_aggs:
            # SELECT/HAVING/ORDER exprs structurally equal to a GROUP BY
            # expr resolve to that key column (standard SQL matching)
            gb_markers = [(g, P.Col(f"__gbm{i}", qualifier="__agg"))
                          for i, g in enumerate(sel.group_by)
                          if not isinstance(g, P.Col)]

            def sub_group(e):
                for g, marker in gb_markers:
                    if e == g:
                        return marker
                for f in getattr(e, "__dataclass_fields__", {}):
                    v = getattr(e, f)
                    if isinstance(v, tuple(_AST_TYPES)):
                        setattr(e, f, sub_group(v))
                    elif isinstance(v, list):
                        setattr(e, f, [sub_group(x)
                                       if isinstance(x, tuple(_AST_TYPES))
                                       else x for x in v])
                return e

            if gb_markers:
                sel.projections = [(sub_group(e), a)
                                   for e, a in sel.projections]
                if sel.having is not None:
                    sel.having = sub_group(sel.having)
                sel.order_by = [(sub_group(e), a) for e, a in sel.order_by]
            projections = [(lower_aggs(e), a) for e, a in sel.projections]
            having = lower_aggs(sel.having) if sel.having is not None else None
            order_by = [(lower_aggs(e), asc) for e, asc in sel.order_by]
            # window specs evaluate over the grouped rows: their member
            # exprs go through the same GROUP-BY matching + agg lowering
            for w, _ in windows:
                if gb_markers:
                    w.partition_by = [sub_group(x) for x in w.partition_by]
                    w.order_by = [(sub_group(x), a) for x, a in w.order_by]
                    w.func.args = [sub_group(x) for x in w.func.args]
                w.partition_by = [lower_aggs(x) for x in w.partition_by]
                w.order_by = [(lower_aggs(x), a) for x, a in w.order_by]
                w.func.args = [lower_aggs(x) for x in w.func.args]

            # group keys: pre-project complex exprs to temp columns
            pre_cols: List[Tuple[str, Expr]] = \
                [(c, ColRef(c)) for c in plan.schema]
            for i, g in enumerate(sel.group_by):
                ge = self._expr(g, scope, None, None)
                if isinstance(ge, ColRef):
                    group_flat.append(ge.name)
                else:
                    tmp = f"__key{i}"
                    pre_cols.append((tmp, ge))
                    group_flat.append(tmp)
                    # let bare SELECT references to this expr resolve too
            agg_specs = []
            for i, (arg_expr, op, tmp) in enumerate(aggs):
                acol = f"__aval{i}"
                pre_cols.append((acol, arg_expr))
                agg_specs.append((acol, op, tmp))
            plan = L.Projection(plan, pre_cols)
            if group_flat:
                plan = L.Aggregate(plan, group_flat, agg_specs)
            else:
                plan = L.Reduce(plan, agg_specs)
            # post-agg scope: group keys + agg temps
            post_scope = Scope()
            marker_i = 0
            for g, gast in zip(group_flat, sel.group_by):
                if isinstance(gast, P.Col):
                    post_scope.add(gast.qualifier or "", gast.name, g)
                else:
                    post_scope.add("__agg", f"__gbm{marker_i}", g)
                    marker_i += 1
            for _, _, tmp in agg_specs:
                post_scope.add("__agg", tmp, tmp)
            # keep original scope for group-key column references
            scope = _restrict_scope(scope, group_flat).merged(post_scope)
            if having is not None:
                plan = L.Filter(plan, self._expr(having, scope, None, None))
            sel = P.Select(projections=projections, order_by=order_by,
                           limit=sel.limit, distinct=sel.distinct)

        if windows:
            plan, scope = self._plan_windows(plan, scope, windows)

        # SELECT list
        out_exprs: List[Tuple[str, Expr]] = []
        out_names: List[str] = []
        for e, alias in sel.projections:
            if isinstance(e, P.StarA):
                names = [
                    f for f in (plan.schema if not group_flat else group_flat)]
                for f in names:
                    nm = f.split("__", 1)[-1]
                    out_exprs.append((nm, ColRef(f)))
                    out_names.append(nm)
                continue
            ex = self._expr(e, scope, None, None)
            name = alias or _default_name(e)
            out_exprs.append((name, ex))
            out_names.append(name)

        # ORDER BY before the final projection rename: resolve against both
        sort_keys: List[Tuple[str, bool]] = []
        extra_sort_cols: List[Tuple[str, Expr]] = []
        for e, asc in sel.order_by:
            if isinstance(e, P.Num) and isinstance(e.value, int):
                sort_keys.append((out_names[e.value - 1], asc))
                continue
            if isinstance(e, P.Col) and e.qualifier is None and \
                    e.name in out_names:
                sort_keys.append((e.name, asc))
                continue
            ex = self._expr(e, scope, None, None)
            tmp = f"__sort{len(extra_sort_cols)}"
            extra_sort_cols.append((tmp, ex))
            sort_keys.append((tmp, asc))

        plan = L.Projection(plan, out_exprs + extra_sort_cols)
        if sel.distinct:
            plan = L.Distinct(plan, out_names)
        if sort_keys:
            plan = L.Sort(plan, [k for k, _ in sort_keys],
                          [a for _, a in sort_keys])
        if extra_sort_cols:
            plan = L.Projection(plan, [(n, ColRef(n)) for n in out_names])
        if sel.limit is not None:
            plan = L.Limit(plan, sel.limit)
        self._cur_schema = prev_schema
        return plan, out_names

    _WINDOW_FUNCS = {"row_number": "row_number", "rank": "rank",
                     "dense_rank": "dense_rank", "ntile": "ntile"}
    # aggregate/navigation window functions → AggWindow ops
    _WINDOW_AGG_FUNCS = {"sum": "sum", "avg": "mean", "min": "min",
                         "max": "max", "count": "count", "lead": "lead",
                         "lag": "lag", "first_value": "first_value",
                         "last_value": "last_value"}

    def _extract_windows(self, sel):
        """Replace WindowA select items with placeholder columns; the
        collected windows are planned AFTER any GROUP BY aggregation
        (SQL evaluates window functions over the grouped rows)."""
        found: List[Tuple[P.WindowA, str]] = []

        def walk_replace(e):
            if isinstance(e, P.WindowA):
                name = e.func.name
                if e.func.star:
                    if name != "count":
                        raise NotImplementedError(
                            f"window function {name}(*) — only COUNT(*)")
                elif name not in self._WINDOW_FUNCS and \
                        name not in self._WINDOW_AGG_FUNCS:
                    raise NotImplementedError(
                        f"window function {name}() — supported: "
                        f"{sorted(self._WINDOW_FUNCS)} + "
                        f"{sorted(self._WINDOW_AGG_FUNCS)}")
                tmp = f"__win{len(found)}"
                found.append((e, tmp))
                return P.Col(tmp, qualifier="__agg")
            for f in getattr(e, "__dataclass_fields__", {}):
                v = getattr(e, f)
                if isinstance(v, tuple(_AST_TYPES)):
                    setattr(e, f, walk_replace(v))
                elif isinstance(v, list):
                    setattr(e, f, [walk_replace(x)
                                   if isinstance(x, tuple(_AST_TYPES))
                                   else x for x in v])
            return e

        sel.projections = [(walk_replace(e), a) for e, a in sel.projections]
        sel.order_by = [(walk_replace(e), a) for e, a in sel.order_by]
        return found

    def _plan_windows(self, plan, scope, found):
        """Plan collected WindowA items as RankWindow/AggWindow nodes."""
        for w, tmp in found:
            pre: List[Tuple[str, Expr]] = [(c, ColRef(c))
                                           for c in plan.schema]
            pkeys: List[str] = []
            for i, pe in enumerate(w.partition_by):
                ex = self._expr(pe, scope, None, None)
                if isinstance(ex, ColRef):
                    pkeys.append(ex.name)
                else:
                    pre.append((f"{tmp}_p{i}", ex))
                    pkeys.append(f"{tmp}_p{i}")
            okeys: List[str] = []
            asc: List[bool] = []
            for i, (oe, a) in enumerate(w.order_by):
                ex = self._expr(oe, scope, None, None)
                if isinstance(ex, ColRef):
                    okeys.append(ex.name)
                else:
                    pre.append((f"{tmp}_o{i}", ex))
                    okeys.append(f"{tmp}_o{i}")
                asc.append(a)
            name = w.func.name
            if name in self._WINDOW_FUNCS and not w.func.star:
                if len(pre) > len(plan.schema):
                    plan = L.Projection(plan, pre)
                op = self._WINDOW_FUNCS[name]
                param = 0
                if op == "ntile":
                    if not (w.func.args and
                            isinstance(w.func.args[0], P.Num)):
                        raise NotImplementedError("NTILE needs a constant")
                    param = int(w.func.args[0].value)
                plan = L.RankWindow(plan, pkeys, okeys, asc,
                                    [(op, param, tmp)])
            else:
                op = "count" if w.func.star else \
                    self._WINDOW_AGG_FUNCS[name]
                param = 0
                if op in ("lead", "lag"):
                    if not okeys:
                        raise NotImplementedError(f"{name} needs ORDER BY")
                    if len(w.func.args) > 2:
                        raise NotImplementedError(
                            f"{name} with an explicit default value")
                    param = 1
                    if len(w.func.args) == 2:
                        if not isinstance(w.func.args[1], P.Num):
                            raise NotImplementedError(
                                f"{name} offset must be a constant")
                        param = int(w.func.args[1].value)
                # value column: pre-project non-trivial args
                if w.func.star:
                    pre.append((f"{tmp}_v", Lit(1)))
                    vcol = f"{tmp}_v"
                else:
                    if not w.func.args:
                        raise SyntaxError(
                            f"window function {name.upper()}() needs an "
                            f"argument (or use COUNT(*))")
                    vex = self._expr(w.func.args[0], scope, None, None)
                    if isinstance(vex, ColRef):
                        vcol = vex.name
                    else:
                        pre.append((f"{tmp}_v", vex))
                        vcol = f"{tmp}_v"
                if len(pre) > len(plan.schema):
                    plan = L.Projection(plan, pre)
                if w.frame is not None:
                    frame = tuple(w.frame)
                elif okeys:
                    frame = ("cumrange",)
                else:
                    frame = ("all",)
                if op in ("lead", "lag"):
                    frame = ("all",)  # navigation ops ignore the frame
                plan = L.AggWindow(plan, pkeys, okeys, asc,
                                   [(op, vcol, frame, param, tmp)])
            scope.add("__agg", tmp, tmp)
        return plan, scope

    # ------------------------------------------------------------------
    # FROM + WHERE: join-graph construction
    # ------------------------------------------------------------------
    def _plan_from_where(self, from_item, where, outer):
        """Plan the FROM list with WHERE-derived equi-joins.

        Comma-joined relations (`from a, b, c where a.x = b.y ...`) are
        the TPC-H idiom; planning them as literal cross products explodes.
        Equality conjuncts between two relations become join keys and the
        join order follows the connectivity graph greedily (the minimal
        version of the join-ordering the reference gets from DuckDB /
        Calcite optimizers)."""
        rels: List = []

        def flatten(item):
            if isinstance(item, P.JoinItem) and item.kind == "cross" and \
                    item.on is None:
                flatten(item.left)
                flatten(item.right)
            else:
                rels.append(item)
        flatten(from_item)

        # LATERAL FLATTEN items apply to the plan built from the other
        # relations (correlated table function): plan the rest first,
        # then explode; WHERE runs after the explode so predicates can
        # reference the flatten output (f.value / f.index)
        flats = [r for r in rels if isinstance(r, P.FlattenItem)]
        if flats:
            rest = [r for r in rels if not isinstance(r, P.FlattenItem)]
            if not rest:
                raise NotImplementedError(
                    "LATERAL FLATTEN requires a base relation")
            item = rest[0]
            for r in rest[1:]:
                item = P.JoinItem(item, r, "cross")
            # conjuncts that touch a flatten alias (f.value / f.index)
            # must run AFTER the explode; everything else goes into the
            # base planning so WHERE-derived equi-joins still form (no
            # accidental cross products)
            fl_aliases = {f.alias.lower() for f in flats}
            fl_cols = {"value", "index"}
            pre: List = []
            post: List = []

            def _touches_flatten(e) -> bool:
                if isinstance(e, P.Col):
                    return ((e.qualifier or "").lower() in fl_aliases
                            or (e.qualifier is None
                                and e.name.lower() in fl_cols))
                import dataclasses
                if not dataclasses.is_dataclass(e):
                    return False
                return any(
                    _touches_flatten(x)
                    for f_ in dataclasses.fields(e)
                    for v_ in [getattr(e, f_.name)]
                    for x in (v_ if isinstance(v_, (list, tuple))
                              else (v_,)))

            def _split_w(e):
                if isinstance(e, P.BinA) and e.op == "&":
                    _split_w(e.left)
                    _split_w(e.right)
                elif _touches_flatten(e):
                    post.append(e)
                else:
                    pre.append(e)
            if where is not None:
                _split_w(where)
            pre_where = None
            for cnj in pre:
                pre_where = cnj if pre_where is None else \
                    P.BinA("&", pre_where, cnj)
            plan, scope = self._plan_from_where(item, pre_where, outer)
            for fl in flats:
                plan, scope = self._plan_flatten(plan, scope, fl)
            for cnj in post:
                plan = self._plan_where(plan, scope, cnj)
            return plan, scope

        planned = [self._from(r, outer) for r in rels]
        if len(planned) == 1:
            plan, scope = planned[0]
            if where is not None:
                plan = self._plan_where(plan, scope, where)
            return plan, scope

        conjuncts: List = []

        def split(e):
            if isinstance(e, P.BinA) and e.op == "&":
                split(e.left)
                split(e.right)
            else:
                conjuncts.append(e)
        if where is not None:
            split(where)

        # classify: cross-relation equality conjuncts become join edges
        def rel_of(col: P.Col) -> Optional[int]:
            hits = []
            for i, (_, s) in enumerate(planned):
                f = self._try_col(col, s)
                if f:
                    hits.append(i)
            return hits[0] if len(hits) == 1 else None

        edges = []   # (rel_i, rel_j, flat_i, flat_j)
        others = []
        single_rel: List[List] = [[] for _ in planned]
        for c in conjuncts:
            if isinstance(c, P.BinA) and c.op == "==" and \
                    isinstance(c.left, P.Col) and isinstance(c.right, P.Col):
                ri, rj = rel_of(c.left), rel_of(c.right)
                if ri is not None and rj is not None and ri != rj:
                    fi = self._try_col(c.left, planned[ri][1])
                    fj = self._try_col(c.right, planned[rj][1])
                    edges.append((ri, rj, fi, fj))
                    continue
            # single-relation plain predicate → filter the relation before
            # joining (shrinks join inputs AND sharpens the cardinality
            # estimates the greedy ordering runs on)
            ri = self._sole_rel(c, planned)
            if ri is not None:
                single_rel[ri].append(c)
            else:
                others.append(c)

        for i, cs in enumerate(single_rel):
            if not cs:
                continue
            p_i, s_i = planned[i]
            prev_schema = getattr(self, "_cur_schema", None)
            self._cur_schema = p_i.schema
            try:
                pred = None
                for c in cs:
                    e = self._expr(c, s_i, None, None)
                    pred = e if pred is None else BinOp("&", pred, e)
            finally:
                self._cur_schema = prev_schema
            planned[i] = (L.Filter(p_i, pred), s_i)

        # greedy cost-based join order (replaces the reference's vendored
        # DuckDB join-order optimizer, bodo/pandas/plan.py
        # get_plan_cardinality): start from the smallest-estimate relation
        # with edges, then repeatedly join the connected relation whose
        # estimated output is smallest
        from bodo_tpu.plan.stats import estimate, join_estimate
        ests = [estimate(p) for p, _ in planned]
        has_edge = {r for e in edges for r in (e[0], e[1])}
        start = min(range(len(planned)),
                    key=lambda i: (i not in has_edge, ests[i][0]))
        used = {start}
        plan, scope = planned[start]
        cur_est, cur_raw = ests[start]
        consumed: set = set()
        while len(used) < len(planned):
            best = None
            for i in range(len(planned)):
                if i in used:
                    continue
                keys_l, keys_r, ids = [], [], []
                for eid, (ri, rj, fi, fj) in enumerate(edges):
                    if eid in consumed:
                        continue
                    if ri in used and rj == i:
                        keys_l.append(fi)
                        keys_r.append(fj)
                        ids.append(eid)
                    elif rj in used and ri == i:
                        keys_l.append(fj)
                        keys_r.append(fi)
                        ids.append(eid)
                if keys_l:
                    out = join_estimate(cur_est, cur_raw, *ests[i])
                    if best is None or out < best[0]:
                        best = (out, i, keys_l, keys_r, ids)
            if best is None:
                # disconnected — cross join with the smallest remainder
                i = min((j for j in range(len(planned)) if j not in used),
                        key=lambda j: ests[j][0])
                plan = self._cross_join(plan, planned[i][0])
                scope = scope.merged(planned[i][1])
                cur_est *= max(ests[i][0], 1.0)
                cur_raw = max(cur_raw, ests[i][1])
                used.add(i)
                continue
            out, i, keys_l, keys_r, ids = best
            plan = L.Join(plan, planned[i][0], keys_l, keys_r, "inner",
                          null_equal=False)
            scope = scope.merged(planned[i][1])
            cur_est, cur_raw = out, max(cur_raw, ests[i][1])
            used.add(i)
            consumed.update(ids)
        # restore FROM-list column order (SELECT * and positional
        # consumers must not see the cost-based join order)
        from_order = [c for p, _ in planned for c in p.schema]
        if list(plan.schema) != from_order:
            plan = L.Projection(plan, [(n, ColRef(n)) for n in from_order
                                       if n in plan.schema])
        # cycle edges not consumed as join keys → equality filters on the
        # joined table (flat names are globally unique, reference directly)
        residual_eq: Optional[Expr] = None
        for eid, (ri, rj, fi, fj) in enumerate(edges):
            if eid in consumed:
                continue
            eq = BinOp("==", ColRef(fi), ColRef(fj))
            residual_eq = eq if residual_eq is None else \
                BinOp("&", residual_eq, eq)
        if residual_eq is not None:
            plan = L.Filter(plan, residual_eq)
        # WHERE residue (subqueries + plain predicates)
        w = None
        for c in others:
            w = c if w is None else P.BinA("&", w, c)
        if w is not None:
            plan = self._plan_where(plan, scope, w)
        return plan, scope

    def _sole_rel(self, c, planned):
        """Index of the single relation that resolves every column in a
        plain conjunct, or None (multi-relation / subquery / ambiguous)."""
        has_sub = [False]

        def look(x):
            if isinstance(x, (P.InSelect, P.Exists, P.ScalarSubquery)):
                has_sub[0] = True
            return x
        self._walk_ast(c, look)
        if has_sub[0]:
            return None
        cols = self._collect_cols(c)
        if not cols:
            return None
        rels = set()
        for col in cols:
            hits = [i for i, (_, s) in enumerate(planned)
                    if self._try_col(col, s)]
            if len(hits) != 1:
                return None
            rels.add(hits[0])
        return rels.pop() if len(rels) == 1 else None

    # ------------------------------------------------------------------
    # WHERE with subquery lowering
    # ------------------------------------------------------------------
    def _plan_flatten(self, plan: L.Node, scope: Scope,
                      fl) -> Tuple[L.Node, Scope]:
        """Apply one LATERAL FLATTEN: explode the input array column and
        expose <alias>.value / <alias>.index in scope (reference:
        BodoSQL/bodosql/kernels/lateral.py lateral_flatten)."""
        if not isinstance(fl.input, P.Col):
            raise NotImplementedError(
                "FLATTEN input must be a column reference")
        flat = self._try_col(fl.input, scope)
        if flat is None:
            raise ValueError(f"unknown FLATTEN input {fl.input.name}")
        tag = self._fresh("fl")
        vname, iname = f"{tag}__value", f"{tag}__index"
        plan = L.Explode(plan, flat, vname, iname, fl.outer)
        scope = scope.merged(Scope())
        scope.add(fl.alias, "value", vname)
        scope.add(fl.alias, "index", iname)
        return plan, scope

    def _plan_where(self, plan: L.Node, scope: Scope, where) -> L.Node:
        conjuncts: List = []

        def split(e):
            if isinstance(e, P.BinA) and e.op == "&":
                split(e.left)
                split(e.right)
            else:
                conjuncts.append(e)
        split(where)

        # dtype-sensitive lowering (CAST of string columns etc.) needs
        # the current plan schema — WHERE runs before _plan_core sets it
        prev_schema = getattr(self, "_cur_schema", None)
        self._cur_schema = plan.schema
        try:
            plain: Optional[Expr] = None
            for c in conjuncts:
                handled, plan = self._try_subquery_conjunct(plan, scope, c)
                if handled:
                    continue
                ex = self._expr(c, scope, None, None)
                plain = ex if plain is None else BinOp("&", plain, ex)
            if plain is not None:
                plan = L.Filter(plan, plain)
            return plan
        finally:
            self._cur_schema = prev_schema

    def _try_subquery_conjunct(self, plan, scope, c):
        """Lower IN/EXISTS/scalar-subquery conjuncts to joins.
        Returns (handled, new_plan)."""
        if isinstance(c, P.InSelect):
            lhs = self._expr(c.operand, scope, None, None)
            return True, self._semi_anti(plan, scope, lhs, c.select,
                                         anti=c.negated)
        if isinstance(c, P.Exists) or (
                isinstance(c, P.UnA) and c.op == "not"
                and isinstance(c.operand, P.Exists)):
            neg = isinstance(c, P.UnA)
            ex = c.operand if neg else c
            anti = ex.negated ^ neg
            return True, self._exists(plan, scope, ex.select, anti=anti)
        # comparison against a scalar subquery (possibly correlated)
        if isinstance(c, P.BinA) and c.op in ("==", "<", "<=", ">", ">=",
                                              "!="):
            for side, other in ((c.left, c.right), (c.right, c.left)):
                if isinstance(side, P.ScalarSubquery):
                    val, plan2, colname = self._scalar_subquery(
                        plan, scope, side.select)
                    other_e = self._expr(other, scope, None, None)
                    sub_e = Lit(val) if colname is None else ColRef(colname)
                    le, re_ = (sub_e, other_e) if side is c.left \
                        else (other_e, sub_e)
                    return True, L.Filter(plan2, BinOp(c.op, le, re_))
        return False, plan

    def _materialize_expr(self, plan: L.Node, e: Expr):
        """Ensure `e` is available as a named column of `plan`."""
        if isinstance(e, ColRef):
            return e.name, plan
        tmp = self._fresh("__mat")
        plan = L.Projection(plan, [(c, ColRef(c)) for c in plan.schema]
                            + [(tmp, e)])
        return tmp, plan

    def _semi_anti(self, plan, scope, lhs: Expr, sub: P.Select, anti: bool):
        node, names = self._plan_core(sub, outer=scope)
        assert len(names) == 1, "IN subquery must select one column"
        tmp = self._fresh("__in")
        node = L.Projection(node, [(tmp, ColRef(names[0]))])
        node = L.Distinct(node, [tmp])
        lcol, plan = self._materialize_expr(plan, lhs)
        if anti:
            j = L.Join(plan, node, [lcol], [tmp], "left", null_equal=False)
            probe = L.Filter(j, UnOp("isna", ColRef(tmp)))
        else:
            probe = L.Join(plan, node, [lcol], [tmp], "inner",
                           null_equal=False)
        keep = [c for c in plan.schema if not c.startswith("__mat")]
        return L.Projection(probe, [(c, ColRef(c)) for c in keep])

    def _exists(self, plan, scope, sub: P.Select, anti: bool):
        """EXISTS with equality correlation → semi/anti join on the
        correlated columns. Non-equality outer references become
        post-join residual filters over a row-id semi join (the general
        unnesting — covers TPC-H Q21)."""
        sub2, corr, residuals, inner_scope = self._decorrelate(sub, scope)
        if not corr:
            raise NotImplementedError(
                "EXISTS without an equality correlation conjunct "
                + ("(only non-equality outer references found)"
                   if residuals else "(uncorrelated)"))
        inner_cols = [ic for _, ic in corr]
        if not residuals:
            sub2.projections = [(c, f"__ex{i}")
                                for i, c in enumerate(inner_cols)]
            node, names = self._plan_core(sub2, outer=None)
            node = L.Distinct(node, names)
            outer_cols = [oc for oc, _ in corr]
            how = "left" if anti else "inner"
            j = L.Join(plan, node, outer_cols, names, how, null_equal=False)
            if anti:
                j = L.Filter(j, UnOp("isna", ColRef(names[0])))
            keep = [c for c in plan.schema]
            return L.Projection(j, [(c, ColRef(c)) for c in keep])

        # general path: tag outer rows with a row id, join on equality
        # correlations keeping multiplicity, filter residuals, then
        # semi/anti on the surviving row ids
        rid = self._fresh("__rid")
        first_col = next(iter(plan.schema))
        plan_rid = L.Window(plan, [(first_col, "rowid", None, rid)])
        # project every residual-referenced inner column with a fresh name
        inner_needed = []
        for e in residuals:
            for c in self._collect_cols(e):
                try:
                    if inner_scope.resolve(c.name, c.qualifier) is not None:
                        inner_needed.append((c.qualifier, c.name))
                except ValueError:
                    inner_needed.append((c.qualifier, c.name))
        inner_needed = list(dict.fromkeys(inner_needed))
        proj = [(c, f"__ex{i}") for i, c in enumerate(inner_cols)]
        inner_name_map = {}
        for i, (q, n) in enumerate(inner_needed):
            nm = f"__er{self._fresh('')}_{i}"
            proj.append((P.Col(n, qualifier=q), nm))
            inner_name_map[(q.lower() if q else None, n.lower())] = nm
        sub2.projections = proj
        node, names = self._plan_core(sub2, outer=None)
        key_names = names[:len(inner_cols)]
        outer_cols = [oc for oc, _ in corr]
        j = L.Join(plan_rid, node, outer_cols, key_names, "inner",
                   null_equal=False)
        # residual conversion: outer cols resolve via the original scope,
        # inner cols via the fresh projected names
        res_scope = Scope()
        res_scope.by_qual = dict(scope.by_qual)
        for k, v in scope.by_col.items():
            res_scope.by_col[k] = list(v)
        for (q, n), nm in inner_name_map.items():
            res_scope.add(q or "", n, nm)
            res_scope.add("", nm, nm)  # rewritten refs resolve directly
        pred = None
        for e in residuals:
            ex = self._expr(self._prefer_inner(e, inner_name_map), res_scope)
            pred = ex if pred is None else BinOp("&", pred, ex)
        f = L.Filter(j, pred)
        matched = L.Distinct(
            L.Projection(f, [(rid + "_m", ColRef(rid))]), [rid + "_m"])
        if anti:
            j2 = L.Join(plan_rid, matched, [rid], [rid + "_m"], "left",
                        null_equal=False)
            out = L.Filter(j2, UnOp("isna", ColRef(rid + "_m")))
        else:
            out = L.Join(plan_rid, matched, [rid], [rid + "_m"], "inner",
                         null_equal=False)
        keep = [c for c in plan.schema]
        return L.Projection(out, [(c, ColRef(c)) for c in keep])

    @staticmethod
    def _walk_ast(e, visit):
        """Shared traversal: call visit(node) on every AST node, covering
        scalar fields AND elements of list/tuple fields (the walker all
        AST passes in this class must use — divergent copies are how
        list-field bugs creep in)."""
        visit(e)
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, tuple(_AST_TYPES)):
                Planner._walk_ast(v, visit)
            elif isinstance(v, (list, tuple)):
                for y in v:
                    if isinstance(y, tuple(_AST_TYPES)):
                        Planner._walk_ast(y, visit)
                    elif isinstance(y, tuple):
                        for z in y:
                            if isinstance(z, tuple(_AST_TYPES)):
                                Planner._walk_ast(z, visit)

    @staticmethod
    def _collect_cols(e) -> List[P.Col]:
        acc: List[P.Col] = []
        Planner._walk_ast(
            e, lambda x: acc.append(x) if isinstance(x, P.Col) else None)
        return acc

    def _prefer_inner(self, e, inner_name_map):
        """Rewrite inner-column refs in a residual AST to their projected
        fresh names (outer refs keep their original qualifier). Rewrites
        Cols in scalar fields and inside list fields (Func.args, IN
        lists, CASE arms)."""
        import copy
        e = copy.deepcopy(e)

        def sub(col: P.Col):
            nm = inner_name_map.get(
                (col.qualifier.lower() if col.qualifier else None,
                 col.name.lower()))
            return P.Col(nm, qualifier=None) if nm is not None else col

        def rewrite(x):
            for f in getattr(x, "__dataclass_fields__", {}):
                v = getattr(x, f)
                if isinstance(v, P.Col):
                    setattr(x, f, sub(v))
                elif isinstance(v, list):
                    setattr(x, f, [sub(y) if isinstance(y, P.Col) else y
                                   for y in v])

        root = P.UnA("not", e)  # wrapper so a top-level Col also rewrites
        Planner._walk_ast(root, rewrite)
        return root.operand

    def _decorrelate(self, sub: P.Select, outer_scope: Scope):
        """Split the subquery WHERE into: equality correlations (pulled
        out as join keys), mixed-reference residual conjuncts (returned
        as ASTs for post-join filtering), and purely-inner conjuncts
        (kept in the subquery). Returns (sub', corr, residuals) where
        corr = [(outer_flat, inner Col AST)]."""
        import copy
        sub = copy.deepcopy(sub)
        # inner scope: plan the FROM cheaply to learn inner names
        probe_planner = Planner({**self.catalog})
        probe_planner.counter = self.counter
        _, inner_scope = probe_planner._from(sub.from_item, None)

        def side_of(col: P.Col):
            try:
                if inner_scope.resolve(col.name, col.qualifier) is not None:
                    return "inner"
            except ValueError:
                return "inner"  # ambiguous within inner → inner
            try:
                if outer_scope.resolve(col.name, col.qualifier) is not None:
                    return "outer"
            except ValueError:
                return "outer"
            return None

        corr: List[Tuple[str, P.Col]] = []
        kept: List = []
        residuals: List = []

        def refs(e, acc):
            if isinstance(e, P.Col):
                acc.append(e)
            for f in getattr(e, "__dataclass_fields__", {}):
                v = getattr(e, f)
                if isinstance(v, tuple(_AST_TYPES)):
                    refs(v, acc)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, tuple(_AST_TYPES)):
                            refs(x, acc)
            return acc

        def split(e):
            if isinstance(e, P.BinA) and e.op == "&":
                split(e.left)
                split(e.right)
                return
            if isinstance(e, P.BinA) and e.op == "==" and \
                    isinstance(e.left, P.Col) and isinstance(e.right, P.Col):
                for a, b in ((e.left, e.right), (e.right, e.left)):
                    if side_of(a) == "inner" and side_of(b) == "outer":
                        corr.append(
                            (outer_scope.resolve(b.name, b.qualifier), a))
                        return
            sides = {side_of(c) for c in refs(e, [])}
            if "outer" in sides:
                residuals.append(e)
            else:
                kept.append(e)

        if sub.where is not None:
            split(sub.where)
            w = None
            for k in kept:
                w = k if w is None else P.BinA("&", w, k)
            sub.where = w
        return sub, corr, residuals, inner_scope

    def _scalar_subquery(self, plan, scope, sub: P.Select):
        """Uncorrelated → execute now, return a literal. Correlated with a
        single aggregate → grouped aggregate joined on correlation keys;
        returns (None, new_plan, value_column)."""
        sub2, corr, residuals, _ = self._decorrelate(sub, scope)
        if residuals:
            raise NotImplementedError(
                "non-equality correlated scalar subquery")
        if not corr:
            node, names = self._plan_core(sub2, outer=None)
            from bodo_tpu.plan.physical import execute
            t = execute(node)
            df = t.to_pandas()
            assert len(names) == 1 and len(df) == 1, \
                "scalar subquery must yield one value"
            return df[names[0]].iloc[0], plan, None
        # correlated aggregate: SELECT agg(e) ... WHERE inner.k = outer.k
        assert len(sub2.projections) == 1, "correlated scalar: one column"
        proj_expr, _ = sub2.projections[0]
        inner_keys = [ic for _, ic in corr]
        outer_keys = [oc for oc, _ in corr]
        val = self._fresh("__sval")
        sub2.projections = [(ic, f"__sk{i}")
                            for i, ic in enumerate(inner_keys)] + \
            [(proj_expr, val)]
        sub2.group_by = list(inner_keys)
        node, names = self._plan_core(sub2, outer=None)
        j = L.Join(plan, node, outer_keys, names[:-1], "inner",
                   null_equal=False)
        return None, j, names[-1]

    # ------------------------------------------------------------------
    # scalar expression conversion
    # ------------------------------------------------------------------
    def _expr(self, e, scope: Scope, _a=None, _b=None) -> Expr:
        if isinstance(e, P.Col):
            flat = scope.resolve(e.name, e.qualifier)
            if flat is None:
                raise ValueError(f"unknown column "
                                 f"{e.qualifier + '.' if e.qualifier else ''}"
                                 f"{e.name}")
            return ColRef(flat)
        if isinstance(e, P.Num):
            return Lit(e.value)
        if isinstance(e, P.Str):
            return Lit(e.value)
        if isinstance(e, P.DateLit):
            return Lit(np.datetime64(e.value))
        if isinstance(e, P.IntervalLit):
            raise NotImplementedError(
                "INTERVAL outside date-literal arithmetic")
        if isinstance(e, P.BinA):
            # constant-fold date ± interval
            folded = _fold_date_arith(e)
            if folded is not None:
                return folded
            left = self._expr(e.left, scope)
            right = self._expr(e.right, scope)
            return self._binop_coerced(e.op, left, right, e)
        if isinstance(e, P.UnA):
            if e.op == "not":
                return UnOp("~", self._expr(e.operand, scope))
            if e.op in ("isnull", "notnull"):
                return UnOp("isna" if e.op == "isnull" else "notna",
                            self._expr(e.operand, scope))
            return UnOp("neg", self._expr(e.operand, scope))
        if isinstance(e, P.Between):
            x = self._expr(e.operand, scope)
            lo = self._binop_coerced(">=", x, self._expr(e.lo, scope), e)
            hi = self._binop_coerced("<=", x, self._expr(e.hi, scope), e)
            both = BinOp("&", lo, hi)
            return UnOp("~", both) if e.negated else both
        if isinstance(e, P.InList):
            x = self._expr(e.operand, scope)
            vals = tuple(v.value for v in e.values
                         if isinstance(v, (P.Num, P.Str)))
            if len(vals) != len(e.values):
                raise NotImplementedError("non-literal IN list")
            if all(isinstance(v, str) for v in vals):
                r = StrPredicate("eq_any", vals, x)
            else:
                r = IsIn(x, vals)
            return UnOp("~", r) if e.negated else r
        if isinstance(e, P.Like):
            x = self._expr(e.operand, scope)
            r = _like_predicate(x, e.pattern)
            return UnOp("~", r) if e.negated else r
        if isinstance(e, P.Case):
            out = self._expr(e.else_, scope) if e.else_ is not None \
                else Lit(np.nan)
            for cond, then in reversed(e.whens):
                out = Where(self._expr(cond, scope),
                            self._expr(then, scope), out)
            return out
        if isinstance(e, P.CastA):
            x = self._expr(e.operand, scope)
            ty = {"integer": dt.INT64, "int": dt.INT64, "bigint": dt.INT64,
                  "smallint": dt.INT32, "double": dt.FLOAT64,
                  "float": dt.FLOAT64, "real": dt.FLOAT32,
                  "decimal": dt.FLOAT64, "numeric": dt.FLOAT64,
                  "varchar": dt.STRING, "text": dt.STRING,
                  "string": dt.STRING, "date": dt.DATE}.get(e.to)
            if ty is None:
                raise NotImplementedError(f"CAST to {e.to}")
            sch = getattr(self, "_cur_schema", None)
            src_t = None
            if sch is not None:
                try:
                    src_t = infer_dtype(x, sch)
                except Exception:
                    src_t = None
            if ty is dt.STRING:
                # string operands pass through; other types format on
                # host via ToChar (bodosql casting_array_kernels to_char)
                if src_t is dt.STRING:
                    return x
                from bodo_tpu.plan.expr import (CodeLUT as _CL,
                                                StrConcat as _SC,
                                                ToChar as _TC)
                if isinstance(x, (DictMap, _CL, _SC)) or \
                        (isinstance(x, Lit) and isinstance(x.value, str)):
                    return x
                return _TC(None, x)
            if src_t is dt.STRING:
                # string → number/date goes through the host parse LUT;
                # TRY_CAST semantics (null on failure) come for free,
                # and plain CAST shares them (no SQL error channel in a
                # traced kernel — the reference's try-variant behavior)
                if ty is dt.DATE:
                    return StrHostFn("to_date", (), x)
                if ty in (dt.FLOAT64, dt.FLOAT32):
                    return StrHostFn("to_number", (), x)
                if ty in (dt.INT64, dt.INT32):
                    # Snowflake rounds half away from zero on
                    # string->integer casts ('99.9' -> 100, not 99)
                    from bodo_tpu.plan.expr import MathFn
                    return Cast(MathFn("round", (0,),
                                       StrHostFn("to_number", (), x)), ty)
            return Cast(x, ty)
        if isinstance(e, P.Extract):
            return DtField(e.field, self._expr(e.operand, scope))
        if isinstance(e, P.Func):
            if e.name in ("year", "month", "day", "hour", "minute", "second",
                          "quarter", "dayofweek", "dayofyear", "week",
                          "weekofyear"):
                return DtField(e.name, self._expr(e.args[0], scope))
            if e.name in ("upper", "lower"):
                return DictMap(e.name, (), self._expr(e.args[0], scope))
            if e.name == "coalesce":
                out = self._expr(e.args[-1], scope)
                for a in reversed(e.args[:-1]):
                    x = self._expr(a, scope)
                    out = Where(UnOp("notna", x), x, out)
                return out
            if e.name == "abs":
                x = self._expr(e.args[0], scope)
                return Where(BinOp("<", x, Lit(0)), UnOp("neg", x), x)
            from bodo_tpu.sql import kernels as K
            return K.lower_func(e.name, [self._expr(a, scope)
                                         for a in e.args])
        if isinstance(e, P.SubstringA):
            return DictMap("substring", (e.start, e.length),
                           self._expr(e.operand, scope))
        if isinstance(e, P.ScalarSubquery):
            node, names = self._plan_core(e.select, outer=None)
            from bodo_tpu.plan.physical import execute
            df = execute(node).to_pandas()
            assert len(df) == 1
            return Lit(df[names[0]].iloc[0])
        raise NotImplementedError(f"expression {e}")

    def _binop_coerced(self, op: str, left: Expr, right: Expr, ast) -> Expr:
        """String-literal comparisons become dictionary predicates;
        DATE/DATETIME physical coercion happens schema-aware in eval_expr."""
        # comparisons of string columns with literals → dict predicates
        if op in ("==", "!=") and isinstance(right, Lit) and \
                isinstance(right.value, str):
            p = StrPredicate("eq_any", (right.value,), left)
            return p if op == "==" else UnOp("~", p)
        if op in ("==", "!=") and isinstance(left, Lit) and \
                isinstance(left.value, str):
            p = StrPredicate("eq_any", (left.value,), right)
            return p if op == "==" else UnOp("~", p)
        return BinOp(op, left, right)


_AST_TYPES = (P.BinA, P.UnA, P.Func, P.Case, P.CastA, P.InList, P.Between,
              P.Like, P.Extract, P.Col, P.Num, P.Str, P.DateLit,
              P.IntervalLit, P.SubstringA, P.ScalarSubquery, P.InSelect,
              P.Exists, P.WindowA)


def _contains_agg(projections) -> bool:
    def walk(e) -> bool:
        if isinstance(e, P.Func) and (e.star or e.name in _AGG_MAP or
                                      e.name == "count"):
            return True
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, tuple(_AST_TYPES)) and walk(v):
                return True
            if isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, tuple(_AST_TYPES)) and walk(x):
                        return True
                    if isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, tuple(_AST_TYPES)) and walk(y):
                                return True
        return False
    return any(walk(e) for e, _ in projections)


def _restrict_scope(scope: Scope, cols: List[str]) -> Scope:
    s = Scope()
    keep = set(cols)
    for (q, c), f in scope.by_qual.items():
        if f in keep:
            s.by_qual[(q, c)] = f
    for c, fs in scope.by_col.items():
        kept = [f for f in fs if f in keep]
        if kept:
            s.by_col[c] = kept
    return s


def _default_name(e) -> str:
    if isinstance(e, P.Col):
        return e.name
    if isinstance(e, P.Func):
        return e.name
    return "expr"


def _fold_date_arith(e: P.BinA) -> Optional[Expr]:
    """DATE 'x' ± INTERVAL 'n' unit → folded datetime literal."""
    def as_date(x):
        if isinstance(x, P.DateLit):
            return np.datetime64(x.value)
        if isinstance(x, P.BinA):
            f = _fold_date_arith(x)
            if isinstance(f, Lit) and isinstance(f.value, np.datetime64):
                return f.value
        return None

    if e.op not in ("+", "-"):
        return None
    d = as_date(e.left)
    iv = e.right if isinstance(e.right, P.IntervalLit) else None
    if d is None or iv is None:
        return None
    sign = 1 if e.op == "+" else -1
    if iv.unit in ("year", "month"):
        months = iv.value * (12 if iv.unit == "year" else 1) * sign
        val = (d.astype("datetime64[M]") + months).astype("datetime64[ns]")
    else:
        mult = {"day": 24 * 3600, "hour": 3600, "minute": 60,
                "second": 1}[iv.unit]
        val = d.astype("datetime64[s]") + sign * iv.value * mult
        val = val.astype("datetime64[ns]")
    return Lit(val)


def _like_predicate(x: Expr, pattern: str) -> Expr:
    if "%" not in pattern and "_" not in pattern:
        return StrPredicate("eq_any", (pattern,), x)
    body = pattern.strip("%")
    if "%" not in body and "_" not in body:
        if pattern.startswith("%") and pattern.endswith("%"):
            return StrPredicate("contains", (body,), x)
        if pattern.endswith("%"):
            return StrPredicate("startswith", (body,), x)
        if pattern.startswith("%"):
            return StrPredicate("endswith", (body,), x)
    import re as _re
    rx = "^" + _re.escape(pattern).replace("%", ".*").replace("_", ".") + "$"
    return StrPredicate("match", (rx,), x)
