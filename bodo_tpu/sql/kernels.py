"""SQL scalar function kernel library.

TPU-native analogue of the reference's Snowflake-compatible kernel
library (BodoSQL/bodosql/kernels/ — 27 modules: string, regexp, numeric,
datetime, conditional, crypto kernels). Here every function lowers to
the hashable expression IR (bodo_tpu/plan/expr.py): numeric/datetime
functions become branch-free VPU arithmetic on device; string functions
become host-dictionary transforms (DictMap/StrHostFn/StrConcat) so only
int32 codes ever touch the TPU.

The registry maps a lower-cased SQL function name to a lowering callable
taking already-lowered argument expressions. Literal-valued parameters
(pad widths, regexp patterns, date-part names) must be literals in the
query text — they parameterize the host-side dictionary transform and
cannot be data-dependent.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from bodo_tpu.plan.expr import (BinOp, Cast, CodeLUT, DateAdd, DateDiff,
                                DateTrunc, DictMap, Expr, Lit, MaskNull,
                                MathFn, StrConcat, StrHostFn, StrLen,
                                StrPredicate, UnOp, Where)
from bodo_tpu.table import dtypes as dt

MONTH_NAMES = ("Jan", "Feb", "Mar", "Apr", "May", "Jun",
               "Jul", "Aug", "Sep", "Oct", "Nov", "Dec")
DAY_NAMES = ("Mon", "Tue", "Wed", "Thu", "Fri", "Sat", "Sun")

_DATE_UNITS = {"year": "year", "yy": "year", "yyyy": "year", "y": "year",
               "quarter": "quarter", "q": "quarter", "qtr": "quarter",
               "month": "month", "mm": "month", "mon": "month",
               "week": "week", "wk": "week", "w": "week",
               "day": "day", "dd": "day", "d": "day",
               "hour": "hour", "hh": "hour",
               "minute": "minute", "mi": "minute",
               "second": "second", "ss": "second", "s": "second"}


def _lit(e: Expr, what: str):
    if not isinstance(e, Lit):
        raise NotImplementedError(f"{what} must be a literal")
    return e.value


def _lit_int(e: Expr, what: str) -> int:
    return int(_lit(e, what))


def _lit_str(e: Expr, what: str) -> str:
    v = _lit(e, what)
    if not isinstance(v, str):
        raise NotImplementedError(f"{what} must be a string literal")
    return v


def _unit(e: Expr) -> str:
    u = _lit_str(e, "date part").lower()
    if u not in _DATE_UNITS:
        raise NotImplementedError(f"date part {u!r}")
    return _DATE_UNITS[u]


def _dictmap(kind: str, params, x: Expr) -> Expr:
    return DictMap(kind, tuple(params), x)


def _nargs(args: List[Expr], lo: int, hi: int = None, name: str = "") -> None:
    hi = lo if hi is None else hi
    if not (lo <= len(args) <= hi):
        raise NotImplementedError(
            f"{name} expects {lo}{'' if hi == lo else f'-{hi}'} args, "
            f"got {len(args)}")


# ---------------------------------------------------------------------------
# lowering functions
# ---------------------------------------------------------------------------

def _concat(args: List[Expr]) -> Expr:
    parts = []
    for a in args:
        if isinstance(a, Lit):
            v = a.value
            parts.append(v if isinstance(v, str) else str(v))
        else:
            parts.append(a)
    return StrConcat(tuple(parts))


def _coalesce(args: List[Expr]) -> Expr:
    out = args[-1]
    for a in reversed(args[:-1]):
        out = Where(UnOp("notna", a), a, out)
    return out


def _fold(op: str, args: List[Expr]) -> Expr:
    out = args[0]
    for a in args[1:]:
        out = BinOp(op, out, a)
    return out


def _math(kind: str, n_params: int = 0):
    def lower(args: List[Expr]) -> Expr:
        _nargs(args, 1, 1 + n_params, kind)
        params = tuple(_lit_int(a, f"{kind} parameter") for a in args[1:])
        return MathFn(kind, params, args[0])
    return lower


def _strmap(kind: str, sig: str):
    """DictMap lowering; sig encodes param kinds after the string arg:
    'i' int literal, 's' str literal, '?s' optional str (default below)."""
    def lower(args: List[Expr]) -> Expr:
        want = len([c for c in sig if c in "is"])
        opt = sig.count("?")
        _nargs(args, 1 + want - opt, 1 + want, kind)
        params, i = [], 1
        for c in sig.replace("?", ""):
            if i < len(args):
                params.append(_lit_int(args[i], kind) if c == "i"
                              else _lit_str(args[i], kind))
            i += 1
        if kind in ("lpad", "rpad") and len(params) == 1:
            params.append(" ")
        return _dictmap(kind, params, args[0])
    return lower


def _trim(kind: str):
    def lower(args: List[Expr]) -> Expr:
        _nargs(args, 1, 2, kind)
        params = (_lit_str(args[1], "trim set"),) if len(args) > 1 else ()
        return _dictmap(kind, params, args[0])
    return lower


def _substr(args: List[Expr]) -> Expr:
    _nargs(args, 2, 3, "substr")
    start = _lit_int(args[1], "substr start")
    length = _lit_int(args[2], "substr length") if len(args) > 2 else None
    return _dictmap("substring", (start, length), args[0])


def _position(args: List[Expr]) -> Expr:
    # POSITION/CHARINDEX(needle, haystack) — note INSTR flips the order
    _nargs(args, 2, 2, "position")
    return StrHostFn("position", (_lit_str(args[0], "needle"),), args[1])


def _instr(args: List[Expr]) -> Expr:
    _nargs(args, 2, 2, "instr")
    return StrHostFn("position", (_lit_str(args[1], "needle"),), args[0])


def _log(args: List[Expr]) -> Expr:
    if len(args) == 1:          # LOG(x) = log10 (Snowflake: LOG(base, x))
        return MathFn("log10", (), args[0])
    base = _lit(args[0], "log base")
    if base == 10:
        return MathFn("log10", (), args[1])
    if base == 2:
        return MathFn("log2", (), args[1])
    return BinOp("/", MathFn("ln", (), args[1]),
                 Lit(float(__import__("math").log(base))))


def _nullif(args: List[Expr]) -> Expr:
    _nargs(args, 2, 2, "nullif")
    return MaskNull(BinOp("==", args[0], args[1]), args[0])


def _pos_int(e: Expr, name: str, lo: int = 1) -> int:
    """Literal int argument with a lower bound (Snowflake raises on
    position/occurrence < 1 rather than searching a negative slice)."""
    v = _lit_int(e, name)
    if v < lo:
        raise ValueError(f"{name} must be >= {lo}, got {v}")
    return v


def _re_flags(params: str) -> str:
    """Snowflake regexp parameter string -> inline-flag prefix ('i' case
    insensitive, 'c' sensitive, 's' dotall, 'm' multiline). When both
    'c' and 'i' appear, the LAST one wins (Snowflake rule)."""
    ci = ""
    for ch in params:
        if ch in "ci":
            ci = ch
    out = "i" if ci == "i" else ""
    if "s" in params:
        out += "s"
    if "m" in params:
        out += "m"
    return f"(?{out})" if out else ""


def _regexp_like(args: List[Expr]) -> Expr:
    _nargs(args, 2, 3, "regexp_like")
    pat = _lit_str(args[1], "pattern")
    if len(args) > 2:
        pat = _re_flags(_lit_str(args[2], "parameters")) + pat
    return StrPredicate("fullmatch", (pat,), args[0])


def _regexp_substr(args: List[Expr]) -> Expr:
    # REGEXP_SUBSTR(s, pat[, position[, occurrence[, params[, group]]]])
    _nargs(args, 2, 6, "regexp_substr")
    pat = _lit_str(args[1], "pattern")
    pos = _pos_int(args[2], "position") if len(args) > 2 else 1
    occ = _pos_int(args[3], "occurrence") if len(args) > 3 else 1
    if len(args) > 4:
        pat = _re_flags(_lit_str(args[4], "parameters")) + pat
    grp = _pos_int(args[5], "group", lo=0) if len(args) > 5 else 0
    return _dictmap("regexp_substr", (pat, pos, occ, grp), args[0])


def _regexp_instr(args: List[Expr]) -> Expr:
    # REGEXP_INSTR(s, pat[, position[, occurrence[, option[, params]]]])
    _nargs(args, 2, 6, "regexp_instr")
    pat = _lit_str(args[1], "pattern")
    pos = _pos_int(args[2], "position") if len(args) > 2 else 1
    occ = _pos_int(args[3], "occurrence") if len(args) > 3 else 1
    opt = _lit_int(args[4], "option") if len(args) > 4 else 0
    if len(args) > 5:
        pat = _re_flags(_lit_str(args[5], "parameters")) + pat
    return StrHostFn("regexp_instr", (pat, pos, occ, opt), args[0])


def _regexp_count2(args: List[Expr]) -> Expr:
    _nargs(args, 2, 4, "regexp_count")
    pat = _lit_str(args[1], "pattern")
    pos = _pos_int(args[2], "position") if len(args) > 2 else 1
    if len(args) > 3:
        pat = _re_flags(_lit_str(args[3], "parameters")) + pat
    return StrHostFn("regexp_count", (pat, pos), args[0])


def _json_extract(args: List[Expr]) -> Expr:
    _nargs(args, 2, 2, "json_extract_path_text")
    return _dictmap("json_extract", (_lit_str(args[1], "path"),), args[0])


def _parse_json(args: List[Expr]) -> Expr:
    _nargs(args, 1, 1, "parse_json")
    return _dictmap("json_canon", (), args[0])


def _strtok(args: List[Expr]) -> Expr:
    _nargs(args, 1, 3, "strtok")
    delim = _lit_str(args[1], "delimiters") if len(args) > 1 else " "
    part = _lit_int(args[2], "part") if len(args) > 2 else 1
    return _dictmap("strtok", (delim, part), args[0])


def _insert_fn(args: List[Expr]) -> Expr:
    _nargs(args, 4, 4, "insert")
    return _dictmap("insert",
                    (_lit_int(args[1], "pos"), _lit_int(args[2], "len"),
                     _lit_str(args[3], "repl")), args[0])


def _editdistance(args: List[Expr]) -> Expr:
    _nargs(args, 2, 3, "editdistance")
    params = (_lit_str(args[1], "other"),)
    if len(args) > 2:
        params += (_lit_int(args[2], "max"),)
    return StrHostFn("editdistance", params, args[0])


def _to_char(args: List[Expr]) -> Expr:
    from bodo_tpu.plan.expr import ToChar
    _nargs(args, 1, 2, "to_char")
    fmt = _lit_str(args[1], "format") if len(args) > 1 else None
    return ToChar(fmt, args[0])


def _space(args: List[Expr]) -> Expr:
    _nargs(args, 1, 1, "space")
    return Lit(" " * _lit_int(args[0], "space count"))


def _char_fn(args: List[Expr]) -> Expr:
    _nargs(args, 1, 1, "char")
    return Lit(chr(_lit_int(args[0], "char code")))


def _monthname(args: List[Expr]) -> Expr:
    from bodo_tpu.plan.expr import DtField
    _nargs(args, 1, 1, "monthname")
    return CodeLUT(MONTH_NAMES, BinOp("-", DtField("month", args[0]), Lit(1)))


def _dayname(args: List[Expr]) -> Expr:
    from bodo_tpu.plan.expr import DtField
    _nargs(args, 1, 1, "dayname")
    return CodeLUT(DAY_NAMES, DtField("dayofweek", args[0]))


def _dateadd(args: List[Expr]) -> Expr:
    _nargs(args, 3, 3, "dateadd")
    return DateAdd(_unit(args[0]), args[1], args[2])


def _datediff(args: List[Expr]) -> Expr:
    _nargs(args, 3, 3, "datediff")
    return DateDiff(_unit(args[0]), args[1], args[2])


def _date_trunc(args: List[Expr]) -> Expr:
    _nargs(args, 2, 2, "date_trunc")
    return DateTrunc(_unit(args[0]), args[1])


def _last_day(args: List[Expr]) -> Expr:
    # last day of month = (trunc(month, d) + 1 month) - 1 day
    _nargs(args, 1, 1, "last_day")
    return DateAdd("day", Lit(-1),
                   DateAdd("month", Lit(1), DateTrunc("month", args[0])))


def _to_number(args: List[Expr]) -> Expr:
    _nargs(args, 1, 1, "to_number")
    return StrHostFn("to_number", (), args[0])


def _to_date(args: List[Expr]) -> Expr:
    _nargs(args, 1, 1, "to_date")
    return StrHostFn("to_date", (), args[0])


def _sha2(args: List[Expr]) -> Expr:
    _nargs(args, 1, 2, "sha2")
    bits = _lit_int(args[1], "sha2 bits") if len(args) > 1 else 256
    return _dictmap("sha2", (bits,), args[0])


def _regexp_replace(args: List[Expr]) -> Expr:
    # REGEXP_REPLACE(s, pat[, repl[, position[, occurrence[, params]]]])
    _nargs(args, 2, 6, "regexp_replace")
    pat = _lit_str(args[1], "pattern")
    repl = _lit_str(args[2], "replacement") if len(args) > 2 else ""
    pos = _pos_int(args[3], "position") if len(args) > 3 else 1
    occ = _pos_int(args[4], "occurrence", lo=0) if len(args) > 4 else 0
    if len(args) > 5:
        pat = _re_flags(_lit_str(args[5], "parameters")) + pat
    return _dictmap("regexp_replace", (pat, repl, pos, occ), args[0])


REGISTRY: Dict[str, Callable[[List[Expr]], Expr]] = {
    # ---- string (reference: bodosql/kernels/string_array_kernels.py) ----
    "length": lambda a: StrLen(a[0]),
    "len": lambda a: StrLen(a[0]),
    "char_length": lambda a: StrLen(a[0]),
    "character_length": lambda a: StrLen(a[0]),
    "trim": _trim("strip"),
    "ltrim": _trim("lstrip"),
    "rtrim": _trim("rstrip"),
    "replace": _strmap("replace", "ss"),
    "lpad": _strmap("lpad", "i?s"),
    "rpad": _strmap("rpad", "i?s"),
    "left": _strmap("left", "i"),
    "right": _strmap("right", "i"),
    "reverse": _strmap("reverse", ""),
    "repeat": _strmap("repeat", "i"),
    "split_part": _strmap("split_part", "si"),
    "initcap": _strmap("initcap", ""),
    "translate": _strmap("translate", "ss"),
    "substr": _substr,
    "concat": _concat,
    "concat_ws": None,  # filled below (needs separator weaving)
    "position": _position,
    "charindex": _position,
    "instr": _instr,
    "ascii": lambda a: StrHostFn("ascii", (), a[0]),
    "startswith": lambda a: StrPredicate(
        "startswith", (_lit_str(a[1], "prefix"),), a[0]),
    "endswith": lambda a: StrPredicate(
        "endswith", (_lit_str(a[1], "suffix"),), a[0]),
    "contains": lambda a: StrPredicate(
        "contains", (_lit_str(a[1], "needle"),), a[0]),
    # ---- regexp (reference: bodosql/kernels/regexp_array_kernels.py) ----
    "regexp_like": _regexp_like,
    "rlike": _regexp_like,
    "regexp_replace": _regexp_replace,
    "regexp_substr": _regexp_substr,
    # Spark/Hive signature: REGEXP_EXTRACT(s, pat, group) — arg 3 is a
    # capture-GROUP index (default 1), not Snowflake's position
    "regexp_extract": lambda a: _dictmap(
        "regexp_substr",
        (_lit_str(a[1], "pattern"), 1, 1,
         _lit_int(a[2], "group") if len(a) > 2 else 1), a[0]),
    "regexp_count": _regexp_count2,
    "regexp_instr": _regexp_instr,
    # ---- json / variant (reference: bodosql/kernels/
    # json_array_kernels.py, variant_array_kernels.py) -----------------
    "json_extract_path_text": _json_extract,
    "get_json_object": _json_extract,
    "parse_json": _parse_json,
    "try_parse_json": _parse_json,
    "to_json": _parse_json,
    # CHECK_JSON: NULL for VALID json, parse-error text for invalid
    "check_json": lambda a: _dictmap("check_json", (), a[0]),
    # ---- casting (reference: bodosql/kernels/casting_array_kernels.py) --
    "to_char": _to_char, "to_varchar": _to_char,
    # ---- string breadth --------------------------------------------------
    "strtok": _strtok,
    "insert": _insert_fn,
    "editdistance": _editdistance,
    "space": _space,
    "char": _char_fn, "chr": _char_fn,
    # ---- crypto (reference: bodosql/kernels/crypto_funcs.py) ----
    "md5": _strmap("md5", ""),
    "md5_hex": _strmap("md5", ""),
    "sha1": _strmap("sha1", ""),
    "sha2": _sha2,
    # ---- numeric (reference: bodosql/kernels/numeric_array_kernels.py) --
    "ceil": _math("ceil"), "ceiling": _math("ceil"),
    "floor": _math("floor"),
    "round": _math("round", 1),
    "trunc": _math("trunc", 1), "truncate": _math("trunc", 1),
    "sqrt": _math("sqrt"), "exp": _math("exp"),
    "ln": _math("ln"), "log": _log,
    "sign": _math("sign"),
    "sin": _math("sin"), "cos": _math("cos"), "tan": _math("tan"),
    "asin": _math("asin"), "acos": _math("acos"), "atan": _math("atan"),
    "degrees": _math("degrees"), "radians": _math("radians"),
    "pow": lambda a: BinOp("**", a[0], a[1]),
    "power": lambda a: BinOp("**", a[0], a[1]),
    "mod": lambda a: BinOp("%", a[0], a[1]),
    "square": lambda a: BinOp("*", a[0], a[0]),
    "to_number": _to_number, "try_to_number": _to_number,
    # ---- conditional (reference: bodosql/kernels/cond_fns.py) -----------
    "iff": lambda a: Where(a[0], a[1], a[2]),
    "if": lambda a: Where(a[0], a[1], a[2]),
    "nullif": _nullif,
    "nvl": _coalesce, "ifnull": _coalesce,
    "nvl2": lambda a: Where(UnOp("notna", a[0]), a[1], a[2]),
    "zeroifnull": lambda a: Where(UnOp("isna", a[0]), Lit(0), a[0]),
    "greatest": lambda a: _fold("max2", a),
    "least": lambda a: _fold("min2", a),
    # ---- datetime (reference: bodosql/kernels/datetime_array_kernels.py)
    "date_trunc": _date_trunc,
    "dateadd": _dateadd, "timestampadd": _dateadd,
    "datediff": _datediff, "timestampdiff": _datediff,
    "last_day": _last_day,
    "monthname": _monthname, "dayname": _dayname,
    "week": None, "weekofyear": None,  # DtField — handled by planner
    "to_date": _to_date, "try_to_date": _to_date,
    # ---- semi-structured (reference: bodosql/kernels/
    # semistructured_array_kernels.py) --------------------------------
    "array_size": None, "get": None, "get_path": None,  # filled below
}


def _array_size(args: List[Expr]) -> Expr:
    from bodo_tpu.plan.expr import NestedFn
    _nargs(args, 1, 1, "array_size")
    return NestedFn("list_len", (), args[0])


def _get(args: List[Expr]) -> Expr:
    from bodo_tpu.plan.expr import NestedFn
    _nargs(args, 2, 2, "get")
    v = _lit(args[1], "get key/index")
    if isinstance(v, str):
        return NestedFn("field", (v,), args[0])
    return NestedFn("list_get", (int(v),), args[0])


def _get_path(args: List[Expr]) -> Expr:
    from bodo_tpu.plan.expr import NestedFn
    _nargs(args, 2, 2, "get_path")
    path = _lit_str(args[1], "path")
    parts = [p.strip("'\"") for p in
             path.replace("]", "").replace("[", ".").split(".") if p]
    if len(parts) != 1:
        # nested values hold scalars in this engine (one dict-encoding
        # level); a multi-part path would address nested-of-nested
        raise NotImplementedError(
            f"multi-part GET_PATH {path!r} (nested values are one "
            f"level deep)")
    part = parts[0]
    if part.lstrip("-").isdigit():
        return NestedFn("list_get", (int(part),), args[0])
    return NestedFn("field", (part,), args[0])


def _concat_ws(args: List[Expr]) -> Expr:
    sep = _lit_str(args[0], "separator")
    parts = []
    for i, a in enumerate(args[1:]):
        if i:
            parts.append(Lit(sep))
        parts.append(a)
    return _concat(parts)


REGISTRY["concat_ws"] = _concat_ws
REGISTRY["array_size"] = _array_size
REGISTRY["get"] = _get
REGISTRY["get_path"] = _get_path
REGISTRY = {k: v for k, v in REGISTRY.items() if v is not None}


def lower_func(name: str, args: List[Expr]) -> Expr:
    """Lower a scalar SQL function call; raises NotImplementedError for
    functions outside the library."""
    fn = REGISTRY.get(name)
    if fn is None:
        raise NotImplementedError(f"function {name}")
    return fn(args)


def is_scalar_func(name: str) -> bool:
    return name in REGISTRY
