"""On-disk SQL plan cache keyed by query hash.

Analogue of the reference's SQL plan cache (bodo/sql_plan_cache.py:1,
BODO_SQL_PLAN_CACHE_DIR). Since our planner is milliseconds (no JVM), the
cache stores the *parsed AST pickle* keyed by (query, catalog schema) —
it mainly saves schema inference on remote scans and documents the
surface; set BODO_TPU_SQL_PLAN_CACHE_DIR to enable.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Optional

from bodo_tpu.config import config


def _key(query: str, schema_sig: str) -> str:
    return hashlib.sha256((query + "\0" + schema_sig).encode()).hexdigest()


def get(query: str, schema_sig: str):
    d = config.sql_plan_cache_dir
    if not d:
        return None
    p = os.path.join(d, _key(query, schema_sig) + ".pkl")
    try:
        with open(p, "rb") as f:
            return pickle.load(f)
    except (OSError, pickle.PickleError, EOFError):
        return None


def put(query: str, schema_sig: str, ast) -> None:
    d = config.sql_plan_cache_dir
    if not d:
        return
    os.makedirs(d, exist_ok=True)
    p = os.path.join(d, _key(query, schema_sig) + ".pkl")
    try:
        with open(p, "wb") as f:
            pickle.dump(ast, f)
    except (OSError, pickle.PickleError):
        pass
