"""On-disk SQL plan cache keyed by query hash.

Analogue of the reference's SQL plan cache (bodo/sql_plan_cache.py:1,
BODO_SQL_PLAN_CACHE_DIR). Since our planner is milliseconds (no JVM), the
cache stores the *parsed AST pickle* keyed by (query, catalog schema) —
it mainly saves schema inference on remote scans and documents the
surface; set BODO_TPU_SQL_PLAN_CACHE_DIR to enable.

A plan-cache hit flows straight into the semantic result cache
(runtime/result_cache.py): the cached AST lowers to the same logical
plan, so its structural fingerprint matches the one the result cache
keyed the previous execution under — a repeat SQL query skips BOTH the
parse and the execution. ``stats()`` exposes hit/miss counters for the
metrics registry (bodo_tpu_sql_plan_cache_total), totals plus a
``by_session`` breakdown labeled with the serving session that issued
the query (runtime/scheduler.py's contextvar; "-" outside the serving
layer — bodo_tpu_sql_plan_cache_session_total).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import sys
import threading
from typing import Dict, Optional

from bodo_tpu.config import config

_stats_mu = threading.Lock()
_stats = {"hits": 0, "misses": 0}
_by_session: Dict[str, Dict[str, int]] = {}


def _session() -> str:
    sch = sys.modules.get("bodo_tpu.runtime.scheduler")
    if sch is None:
        return "-"
    try:
        return sch.current_session() or "-"
    except Exception:  # noqa: BLE001 - attribution is best-effort
        return "-"


def stats() -> dict:
    with _stats_mu:
        out = dict(_stats)
        out["by_session"] = {sid: dict(row)
                             for sid, row in _by_session.items()}
        return out


def reset_stats() -> None:
    with _stats_mu:
        _stats["hits"] = 0
        _stats["misses"] = 0
        _by_session.clear()


def _count(key: str) -> None:
    sid = _session()
    with _stats_mu:
        _stats[key] += 1
        row = _by_session.setdefault(sid, {"hits": 0, "misses": 0})
        row[key] += 1


def _key(query: str, schema_sig: str) -> str:
    return hashlib.sha256((query + "\0" + schema_sig).encode()).hexdigest()


def get(query: str, schema_sig: str):
    d = config.sql_plan_cache_dir
    if not d:
        return None
    p = os.path.join(d, _key(query, schema_sig) + ".pkl")
    try:
        with open(p, "rb") as f:
            ast = pickle.load(f)
    except (OSError, pickle.PickleError, EOFError):
        _count("misses")
        return None
    _count("hits")
    return ast


def put(query: str, schema_sig: str, ast) -> None:
    d = config.sql_plan_cache_dir
    if not d:
        return
    os.makedirs(d, exist_ok=True)
    p = os.path.join(d, _key(query, schema_sig) + ".pkl")
    try:
        with open(p, "wb") as f:
            pickle.dump(ast, f)
    except (OSError, pickle.PickleError):
        pass
