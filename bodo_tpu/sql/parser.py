"""SQL tokenizer + recursive-descent parser → AST.

Replaces the reference's JVM Calcite parser (BodoSQL/calcite_sql/,
RelationalAlgebraGenerator.java:31) with a self-contained Python parser
covering the analytical SQL core: SELECT [DISTINCT], FROM with aliases,
subqueries and CTEs (WITH), INNER/LEFT/RIGHT/CROSS JOIN ... ON, WHERE,
GROUP BY, HAVING, ORDER BY [ASC|DESC] [NULLS LAST], LIMIT, CASE WHEN,
BETWEEN, IN (list|subquery), EXISTS, LIKE, IS [NOT] NULL, CAST, EXTRACT,
DATE/INTERVAL literals, and the standard operator precedence chain.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclass
class Select:
    projections: List[Tuple[Any, Optional[str]]]  # (expr, alias)
    from_item: Any = None
    where: Any = None
    group_by: List[Any] = field(default_factory=list)
    having: Any = None
    order_by: List[Tuple[Any, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    distinct: bool = False
    ctes: List[Tuple[str, "Select"]] = field(default_factory=list)


@dataclass
class UnionSel:
    selects: List["Select"]
    alls: List[bool] = field(default_factory=list)  # per UNION operator
    order_by: List[Tuple[Any, bool]] = field(default_factory=list)
    limit: Optional[int] = None


@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass
class SubSelect:
    select: Select
    alias: str


@dataclass
class JoinItem:
    left: Any
    right: Any
    kind: str          # inner/left/right/outer/cross
    on: Any = None
    using: Any = None  # list of column names for JOIN ... USING (a, b)


@dataclass
class Col:
    name: str
    qualifier: Optional[str] = None


@dataclass
class Num:
    value: Any


@dataclass
class Str:
    value: str


@dataclass
class DateLit:
    value: str


@dataclass
class IntervalLit:
    value: int
    unit: str          # year/month/day/hour/minute/second


@dataclass
class BinA:
    op: str
    left: Any
    right: Any


@dataclass
class UnA:
    op: str            # not / neg / isnull / notnull
    operand: Any


@dataclass
class Func:
    name: str
    args: List[Any]
    distinct: bool = False
    star: bool = False  # count(*)


@dataclass
class WindowA:
    """fn(...) OVER (PARTITION BY ... ORDER BY ... [ROWS BETWEEN]).
    frame: None (default), or ("rows", lo, hi) with lo/hi row offsets
    (negative = preceding, None = unbounded on that end)."""
    func: "Func"
    partition_by: List[Any]
    order_by: List[Tuple[Any, bool]]  # (expr, ascending)
    frame: Any = None


@dataclass
class Case:
    whens: List[Tuple[Any, Any]]
    else_: Any = None


@dataclass
class CastA:
    operand: Any
    to: str
    try_: bool = False


@dataclass
class FlattenItem:
    """LATERAL FLATTEN(input => <expr>) [AS] alias — the table function
    form of array explode (reference: BodoSQL lateral.py FLATTEN)."""
    input: Any
    alias: str = "f"
    outer: bool = False


@dataclass
class InList:
    operand: Any
    values: List[Any]
    negated: bool = False


@dataclass
class InSelect:
    operand: Any
    select: Select
    negated: bool = False


@dataclass
class Exists:
    select: Select
    negated: bool = False


@dataclass
class ScalarSubquery:
    select: Select


@dataclass
class Between:
    operand: Any
    lo: Any
    hi: Any
    negated: bool = False


@dataclass
class Like:
    operand: Any
    pattern: str
    negated: bool = False


@dataclass
class Extract:
    field: str
    operand: Any


@dataclass
class SubstringA:
    operand: Any
    start: int
    length: Optional[int]


@dataclass
class StarA:
    qualifier: Optional[str] = None


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*\n?)
  | (?P<num>\d+\.\d*|\.\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<qid>"[^"]+")
  | (?P<id>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><>|!=|>=|<=|=>|\|\||[=<>+\-*/%(),.;])
""", re.VERBOSE)


def tokenize(sql: str) -> List[Tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise SyntaxError(f"bad SQL at: {sql[pos:pos+30]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "id":
            out.append(("kw" if text.upper() in _KEYWORDS else "id", text))
        elif kind == "qid":
            out.append(("id", text[1:-1]))
        elif kind == "str":
            out.append(("str", text[1:-1].replace("''", "'")))
        else:
            out.append((kind, text))
    out.append(("eof", ""))
    return out


_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "AS", "AND", "OR", "NOT", "IN", "IS", "NULL", "LIKE", "BETWEEN",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "CROSS", "ON",
    "CASE", "WHEN", "THEN", "ELSE", "END", "CAST", "TRY_CAST", "DISTINCT",
    "EXISTS", "LATERAL",
    "ASC", "DESC", "DATE", "INTERVAL", "EXTRACT", "WITH", "UNION", "ALL",
    "SUBSTRING", "FOR", "NULLS", "FIRST", "LAST", "TRUE", "FALSE",
    "OVER", "PARTITION",
}


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0

    # -- token helpers ----------------------------------------------------
    def peek(self, k: int = 0):
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def kw(self, *words) -> bool:
        t, v = self.peek()
        return t == "kw" and v.upper() in words

    def eat_kw(self, *words) -> str:
        if not self.kw(*words):
            raise SyntaxError(f"expected {words}, got {self.peek()}")
        v = self.toks[self.i][1].upper()
        self.i += 1
        return v

    def try_kw(self, *words) -> bool:
        if self.kw(*words):
            self.i += 1
            return True
        return False

    def eat_op(self, op: str):
        t, v = self.peek()
        if t != "op" or v != op:
            raise SyntaxError(f"expected {op!r}, got {self.peek()}")
        self.i += 1

    def try_op(self, op: str) -> bool:
        t, v = self.peek()
        if t == "op" and v == op:
            self.i += 1
            return True
        return False

    def ident(self) -> str:
        t, v = self.peek()
        if t != "id":
            raise SyntaxError(f"expected identifier, got {self.peek()}")
        self.i += 1
        return v

    # -- grammar ----------------------------------------------------------
    def parse(self):
        sel = self.select_stmt()
        self.try_op(";")
        t, _ = self.peek()
        if t != "eof":
            raise SyntaxError(f"trailing tokens at {self.peek()}")
        return sel

    def select_stmt(self):
        ctes = []
        if self.try_kw("WITH"):
            while True:
                name = self.ident()
                self.eat_kw("AS")
                self.eat_op("(")
                ctes.append((name, self.select_stmt()))
                self.eat_op(")")
                if not self.try_op(","):
                    break
        sel = self.select_core()
        sels = [sel]
        alls: List[bool] = []
        while self.kw("UNION"):
            self.eat_kw("UNION")
            alls.append(self.try_kw("ALL"))
            sels.append(self.select_core())
        if len(sels) > 1:
            # ORDER BY / LIMIT written after the chain are consumed by the
            # last arm's select_core — they belong to the whole union
            for arm in sels[:-1]:
                if arm.order_by or arm.limit is not None:
                    raise NotImplementedError(
                        "ORDER BY/LIMIT inside a UNION arm — wrap the arm "
                        "in a subquery")
            last = sels[-1]
            u = UnionSel(sels, alls, order_by=last.order_by,
                         limit=last.limit)
            last.order_by = []
            last.limit = None
            if ctes:
                raise NotImplementedError("WITH + UNION (wrap in subquery)")
            return u
        sel.ctes = ctes
        return sel

    def select_core(self) -> Select:
        self.eat_kw("SELECT")
        distinct = self.try_kw("DISTINCT")
        projs = []
        while True:
            if self.try_op("*"):
                projs.append((StarA(), None))
            elif self.peek()[0] == "id" and self.peek(1)[1] == "." and \
                    self.peek(2)[1] == "*":
                q = self.ident()
                self.eat_op(".")
                self.eat_op("*")
                projs.append((StarA(q), None))
            else:
                e = self.expr()
                alias = None
                if self.try_kw("AS"):
                    alias = self.ident()
                elif self.peek()[0] == "id":
                    alias = self.ident()
                projs.append((e, alias))
            if not self.try_op(","):
                break
        sel = Select(projections=projs, distinct=distinct)
        if self.try_kw("FROM"):
            sel.from_item = self.from_clause()
        if self.try_kw("WHERE"):
            sel.where = self.expr()
        if self.kw("GROUP"):
            self.eat_kw("GROUP")
            self.eat_kw("BY")
            while True:
                sel.group_by.append(self.expr())
                if not self.try_op(","):
                    break
        if self.try_kw("HAVING"):
            sel.having = self.expr()
        if self.kw("ORDER"):
            self.eat_kw("ORDER")
            self.eat_kw("BY")
            while True:
                e = self.expr()
                asc = True
                if self.try_kw("DESC"):
                    asc = False
                else:
                    self.try_kw("ASC")
                if self.try_kw("NULLS"):
                    self.eat_kw("FIRST", "LAST")
                sel.order_by.append((e, asc))
                if not self.try_op(","):
                    break
        if self.try_kw("LIMIT"):
            t, v = self.peek()
            if t != "num":
                raise SyntaxError("LIMIT expects a number")
            self.i += 1
            sel.limit = int(v)
        return sel

    def from_clause(self):
        item = self.table_item()
        while True:
            if self.try_op(","):
                right = self.table_item()
                item = JoinItem(item, right, "cross")
            elif self.kw("JOIN", "INNER", "LEFT", "RIGHT", "CROSS", "FULL"):
                kind = "inner"
                if self.try_kw("INNER"):
                    pass
                elif self.try_kw("LEFT"):
                    self.try_kw("OUTER")
                    kind = "left"
                elif self.try_kw("RIGHT"):
                    self.try_kw("OUTER")
                    kind = "right"
                elif self.try_kw("CROSS"):
                    kind = "cross"
                elif self.try_kw("FULL"):
                    self.try_kw("OUTER")
                    kind = "outer"
                self.eat_kw("JOIN")
                right = self.table_item()
                on = None
                using = None
                if kind != "cross":
                    # USING is not in _KEYWORDS; it tokenizes as an id
                    if (self.peek()[0] == "id" and
                            self.peek()[1].upper() == "USING"):
                        self.i += 1
                        self.eat_op("(")
                        using = [self.ident()]
                        while self.try_op(","):
                            using.append(self.ident())
                        self.eat_op(")")
                    else:
                        self.eat_kw("ON")
                        on = self.expr()
                item = JoinItem(item, right, kind, on, using)
            else:
                return item

    def table_item(self):
        if self.try_op("("):
            sub = self.select_stmt()
            self.eat_op(")")
            self.try_kw("AS")
            alias = self.ident()
            return SubSelect(sub, alias)
        if self.try_kw("LATERAL"):
            return self._flatten_item()
        if self.peek()[0] == "id" and \
                self.peek()[1].upper() in ("FLATTEN", "TABLE") and \
                self.peek(1) == ("op", "("):
            if self.peek()[1].upper() == "TABLE":
                self.i += 1          # TABLE ( FLATTEN (...) ) alias
                self.eat_op("(")
                item = self._flatten_item()
                self.eat_op(")")
                self.try_kw("AS")
                if self.peek()[0] == "id":
                    item.alias = self.ident()
                return item
            return self._flatten_item()
        name = self.ident()
        alias = None
        if self.try_kw("AS"):
            alias = self.ident()
        elif self.peek()[0] == "id" and self.peek()[1].upper() != "USING":
            # USING introduces a join-key list, never a table alias
            alias = self.ident()
        return TableRef(name, alias)

    def _flatten_item(self) -> "FlattenItem":
        """FLATTEN(input => expr [, outer => true|false]) [AS] alias."""
        nm = self.ident()
        if nm.upper() != "FLATTEN":
            raise NotImplementedError(
                f"LATERAL {nm} (only FLATTEN is supported)")
        self.eat_op("(")
        inp = None
        outer = False
        while True:
            t, v = self.peek()
            if t in ("id", "kw") and v.upper() in ("INPUT", "OUTER") and \
                    self.peek(1) == ("op", "=>"):
                key = v.upper()
                self.i += 2
                if key == "INPUT":
                    inp = self.expr()
                else:
                    outer = self.eat_kw("TRUE", "FALSE") == "TRUE"
            else:
                inp = self.expr()
            if not self.try_op(","):
                break
        self.eat_op(")")
        if inp is None:
            raise SyntaxError("FLATTEN requires an input argument")
        self.try_kw("AS")
        alias = "f"
        if self.peek()[0] == "id" and \
                self.peek()[1].upper() != "USING":
            alias = self.ident()
        return FlattenItem(inp, alias, outer)

    # -- expressions (precedence climbing) --------------------------------
    def expr(self):
        return self.or_expr()

    def _over_clause(self, fn: Func) -> WindowA:
        self.eat_kw("OVER")
        self.eat_op("(")
        partition: List[Any] = []
        order: List[Tuple[Any, bool]] = []
        if self.try_kw("PARTITION"):
            self.eat_kw("BY")
            partition.append(self.expr())
            while self.try_op(","):
                partition.append(self.expr())
        if self.try_kw("ORDER"):
            self.eat_kw("BY")
            while True:
                e = self.expr()
                asc = True
                if self.try_kw("DESC"):
                    asc = False
                else:
                    self.try_kw("ASC")
                order.append((e, asc))
                if not self.try_op(","):
                    break
        frame = None
        if self._try_word("ROWS"):
            if not self.try_kw("BETWEEN"):
                # shorthand <bound> = BETWEEN <bound> AND CURRENT ROW;
                # SQL requires the bound not to follow the current row
                lo = self._frame_bound()
                if lo == "unb_foll" or (isinstance(lo, int) and lo > 0):
                    raise SyntaxError(
                        "ROWS <bound> shorthand requires PRECEDING or "
                        "CURRENT ROW (use ROWS BETWEEN ... AND n "
                        "FOLLOWING)")
                frame = ("rows", None if lo == "unb_prec" else lo, 0)
            else:
                lo = self._frame_bound()
                self.eat_kw("AND")
                hi = self._frame_bound()
                if lo == "unb_foll" or hi == "unb_prec":
                    raise SyntaxError(
                        "frame start may not be UNBOUNDED FOLLOWING and "
                        "frame end may not be UNBOUNDED PRECEDING")
                lo = None if lo == "unb_prec" else lo
                hi = None if hi == "unb_foll" else hi
                if lo is not None and hi is not None and lo > hi:
                    raise SyntaxError(
                        f"frame start ({lo}) follows frame end ({hi})")
                frame = ("rows", lo, hi)
        elif self._try_word("RANGE"):
            # only the default RANGE frame shapes are modeled
            if not self.try_kw("BETWEEN"):
                b = self._frame_bound()
                if b != "unb_prec":
                    raise NotImplementedError("RANGE with a value offset")
            else:
                lo = self._frame_bound()
                self.eat_kw("AND")
                hi = self._frame_bound()
                if not (lo == "unb_prec" and hi in (0, "unb_foll")):
                    raise NotImplementedError("RANGE with value offsets")
                if hi == "unb_foll":
                    frame = ("rows", None, None)  # whole partition
        self.eat_op(")")
        return WindowA(fn, partition, order, frame)

    def _try_word(self, word: str) -> bool:
        """Match a non-reserved word token (id or kw) case-insensitively."""
        t, v = self.peek()
        if t in ("id", "kw") and v.upper() == word:
            self.i += 1
            return True
        return False

    def _frame_bound(self):
        """UNBOUNDED PRECEDING/FOLLOWING | CURRENT ROW | n PRECEDING |
        n FOLLOWING → row offset (int, 0 = current row) or the markers
        "unb_prec"/"unb_foll" so the caller can validate direction."""
        if self._try_word("UNBOUNDED"):
            if self._try_word("PRECEDING"):
                return "unb_prec"
            if self._try_word("FOLLOWING"):
                return "unb_foll"
            raise SyntaxError("expected PRECEDING/FOLLOWING")
        if self._try_word("CURRENT"):
            if not self._try_word("ROW"):
                raise SyntaxError("expected CURRENT ROW")
            return 0
        t, v = self.peek()
        if t == "num":
            self.i += 1
            n = int(v)
            if self._try_word("PRECEDING"):
                return -n
            if self._try_word("FOLLOWING"):
                return n
            raise SyntaxError("expected PRECEDING/FOLLOWING")
        raise SyntaxError(f"bad frame bound at {self.peek()}")

    def or_expr(self):
        e = self.and_expr()
        while self.try_kw("OR"):
            e = BinA("|", e, self.and_expr())
        return e

    def and_expr(self):
        e = self.not_expr()
        while self.try_kw("AND"):
            e = BinA("&", e, self.not_expr())
        return e

    def not_expr(self):
        if self.try_kw("NOT"):
            return UnA("not", self.not_expr())
        return self.cmp_expr()

    def cmp_expr(self):
        e = self.concat_expr()
        while True:
            t, v = self.peek()
            if t == "op" and v in ("=", "<>", "!=", "<", "<=", ">", ">="):
                self.i += 1
                op = {"=": "==", "<>": "!="}.get(v, v)
                e = BinA(op, e, self.concat_expr())
            elif self.kw("IS"):
                self.eat_kw("IS")
                neg = self.try_kw("NOT")
                self.eat_kw("NULL")
                e = UnA("notnull" if neg else "isnull", e)
            elif self.kw("BETWEEN") or (self.kw("NOT") and
                                        self.peek(1)[1].upper() == "BETWEEN"):
                neg = self.try_kw("NOT")
                self.eat_kw("BETWEEN")
                lo = self.concat_expr()
                self.eat_kw("AND")
                hi = self.concat_expr()
                e = Between(e, lo, hi, neg)
            elif self.kw("IN") or (self.kw("NOT") and
                                   self.peek(1)[1].upper() == "IN"):
                neg = self.try_kw("NOT")
                self.eat_kw("IN")
                self.eat_op("(")
                if self.kw("SELECT", "WITH"):
                    sub = self.select_stmt()
                    self.eat_op(")")
                    e = InSelect(e, sub, neg)
                else:
                    vals = [self.expr()]
                    while self.try_op(","):
                        vals.append(self.expr())
                    self.eat_op(")")
                    e = InList(e, vals, neg)
            elif self.kw("LIKE") or (self.kw("NOT") and
                                     self.peek(1)[1].upper() == "LIKE"):
                neg = self.try_kw("NOT")
                self.eat_kw("LIKE")
                t2, v2 = self.peek()
                if t2 != "str":
                    raise SyntaxError("LIKE expects a string literal")
                self.i += 1
                e = Like(e, v2, neg)
            else:
                return e

    def concat_expr(self):
        e = self.add_expr()
        while True:
            t, v = self.peek()
            if t == "op" and v == "||":
                self.i += 1
                rhs = self.add_expr()
                # flatten a || b || c into one CONCAT call
                if isinstance(e, Func) and e.name == "concat":
                    e = Func("concat", e.args + [rhs])
                else:
                    e = Func("concat", [e, rhs])
            else:
                return e

    def add_expr(self):
        e = self.mul_expr()
        while True:
            t, v = self.peek()
            if t == "op" and v in ("+", "-"):
                self.i += 1
                e = BinA(v, e, self.mul_expr())
            else:
                return e

    def mul_expr(self):
        e = self.unary_expr()
        while True:
            t, v = self.peek()
            if t == "op" and v in ("*", "/", "%"):
                self.i += 1
                e = BinA(v, e, self.unary_expr())
            else:
                return e

    def unary_expr(self):
        t, v = self.peek()
        if t == "op" and v == "-":
            self.i += 1
            return UnA("neg", self.unary_expr())
        if t == "op" and v == "+":
            self.i += 1
            return self.unary_expr()
        return self.primary()

    def primary(self):
        t, v = self.peek()
        if t == "op" and v == "(":
            self.i += 1
            if self.kw("SELECT", "WITH"):
                sub = self.select_stmt()
                self.eat_op(")")
                return ScalarSubquery(sub)
            e = self.expr()
            self.eat_op(")")
            return e
        if t == "num":
            self.i += 1
            return Num(float(v) if "." in v else int(v))
        if t == "str":
            self.i += 1
            return Str(v)
        if self.kw("TRUE"):
            self.i += 1
            return Num(True)
        if self.kw("FALSE"):
            self.i += 1
            return Num(False)
        if self.kw("NULL"):
            self.i += 1
            return Num(None)
        if self.kw("DATE"):
            self.i += 1
            t2, v2 = self.peek()
            if t2 != "str":
                raise SyntaxError("DATE expects a string literal")
            self.i += 1
            return DateLit(v2)
        if self.kw("INTERVAL"):
            self.i += 1
            t2, v2 = self.peek()
            if t2 != "str":
                raise SyntaxError("INTERVAL expects a quoted quantity")
            self.i += 1
            unit = self.ident().lower().rstrip("s")
            return IntervalLit(int(v2), unit)
        if self.kw("CASE"):
            self.i += 1
            whens = []
            else_ = None
            while self.try_kw("WHEN"):
                c = self.expr()
                self.eat_kw("THEN")
                whens.append((c, self.expr()))
            if self.try_kw("ELSE"):
                else_ = self.expr()
            self.eat_kw("END")
            return Case(whens, else_)
        if self.kw("CAST", "TRY_CAST"):
            is_try = self.peek()[1].upper() == "TRY_CAST"
            self.i += 1
            self.eat_op("(")
            e = self.expr()
            self.eat_kw("AS")
            ty = self.ident()
            # swallow precision args e.g. DECIMAL(12,2)
            if self.try_op("("):
                while not self.try_op(")"):
                    self.i += 1
            self.eat_op(")")
            return CastA(e, ty.lower(), is_try)
        if self.kw("EXTRACT"):
            self.i += 1
            self.eat_op("(")
            fld = self.ident().lower()
            self.eat_kw("FROM")
            e = self.expr()
            self.eat_op(")")
            return Extract(fld, e)
        if self.kw("SUBSTRING"):
            self.i += 1
            self.eat_op("(")
            e = self.expr()
            if not self.try_kw("FROM"):
                self.eat_op(",")
            start = self.expr()
            length = None
            if self.try_kw("FOR") or self.try_op(","):
                length = self.expr()
            self.eat_op(")")
            if not isinstance(start, Num) or (
                    length is not None and not isinstance(length, Num)):
                raise NotImplementedError("non-constant substring bounds")
            return SubstringA(e, int(start.value),
                              int(length.value) if length else None)
        if self.kw("EXISTS"):
            self.i += 1
            self.eat_op("(")
            sub = self.select_stmt()
            self.eat_op(")")
            return Exists(sub)
        # LEFT/RIGHT are join keywords but also scalar functions when
        # immediately followed by an argument list
        if t == "kw" and v.upper() in ("LEFT", "RIGHT") and \
                self.peek(1) == ("op", "("):
            t = "id"
            self.toks[self.i] = ("id", v)
        if t == "id":
            name = self.ident()
            if self.try_op("("):           # function call
                if self.try_op("*"):
                    self.eat_op(")")
                    fn = Func(name.lower(), [], star=True)
                    if self.kw("OVER"):
                        return self._over_clause(fn)
                    return fn
                distinct = self.try_kw("DISTINCT")
                args = []
                if not self.try_op(")"):
                    args.append(self.expr())
                    while self.try_op(","):
                        args.append(self.expr())
                    self.eat_op(")")
                fn = Func(name.lower(), args, distinct=distinct)
                if self.kw("OVER"):
                    return self._over_clause(fn)
                return fn
            if self.try_op("."):
                col = self.ident()
                return Col(col, qualifier=name)
            return Col(name)
        raise SyntaxError(f"unexpected token {self.peek()}")


def parse_sql(sql: str) -> Select:
    return Parser(sql).parse()
