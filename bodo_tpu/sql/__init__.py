"""SQL surface: BodoSQLContext analogue (reference
BodoSQL/bodosql/context.py:111 BodoSQLContext, :504 sql())."""

from __future__ import annotations

from typing import Dict, Optional

import pandas as pd

from bodo_tpu.plan import logical as L
from bodo_tpu.sql.parser import parse_sql
from bodo_tpu.sql.planner import Planner

__all__ = ["BodoSQLContext"]


class BodoSQLContext:
    """Register tables (pandas frames, lazy frames, or parquet paths) and
    run SQL against them. Queries lower to the same logical plan /
    executor as the dataframe frontend."""

    def __init__(self, tables: Optional[Dict] = None):
        self._tables: Dict[str, L.Node] = {}
        for name, t in (tables or {}).items():
            self.add_table(name, t)

    def add_table(self, name: str, table) -> None:
        from bodo_tpu.pandas_api.frame import BodoDataFrame
        if isinstance(table, BodoDataFrame):
            self._tables[name] = table._plan
        elif isinstance(table, pd.DataFrame):
            self._tables[name] = L.FromPandas(table)
        elif isinstance(table, str):
            self._tables[name] = L.ReadParquet(table)
        elif isinstance(table, L.Node):
            self._tables[name] = table
        else:
            raise TypeError(f"cannot register table {name}: {type(table)}")

    def remove_table(self, name: str) -> None:
        del self._tables[name]

    def _schema_sig(self) -> str:
        return repr(sorted((n, tuple(p.schema)) for n, p in
                           self._tables.items()))

    def sql(self, query: str):
        """Plan + execute; returns a lazy BodoDataFrame (DDL statements
        execute immediately and return a status/metadata frame, the
        reference's direct-DDL path: BodoSQL context.py:531)."""
        ddl = self._try_ddl(query)
        if ddl is not None:
            return ddl
        from bodo_tpu.pandas_api.frame import BodoDataFrame
        from bodo_tpu.sql import plan_cache
        sig = self._schema_sig()
        ast = plan_cache.get(query, sig)
        if ast is None:
            ast = parse_sql(query)
            # pickle to disk BEFORE planning — the planner rewrites AST
            # nodes in place, so only cache-served objects need copying
            plan_cache.put(query, sig, ast)
        else:
            import copy
            ast = copy.deepcopy(ast)
        plan, names = Planner(self._tables).plan(ast)
        return BodoDataFrame(plan)

    def _try_ddl(self, query: str):
        """Handle DDL statements (CREATE TABLE/VIEW AS, DROP TABLE,
        DESCRIBE, SHOW TABLES); None for ordinary queries."""
        import re
        q = query.strip().rstrip(";")
        up = q.upper()

        m = re.match(
            r"CREATE\s+(OR\s+REPLACE\s+)?(TABLE|VIEW)\s+(\w+)\s+AS\s+",
            q, re.IGNORECASE)
        if m:
            name = m.group(3).lower()
            if name in self._tables and not m.group(1):
                raise ValueError(f"table {name!r} already exists "
                                 f"(use CREATE OR REPLACE)")
            body = q[m.end():]
            result = self.sql(body)
            if m.group(2).upper() == "VIEW":
                # views stay lazy: re-planned against live sources
                self._tables[name] = result._plan
            else:
                # tables materialize now (CTAS snapshot semantics)
                from bodo_tpu.plan.physical import execute
                self._tables[name] = L.FromPandas(execute(result._plan))
            return pd.DataFrame(
                {"status": [f"{m.group(2).capitalize()} {name} "
                            f"successfully created."]})

        m = re.match(r"DROP\s+(TABLE|VIEW)\s+(IF\s+EXISTS\s+)?(\w+)\s*$",
                     q, re.IGNORECASE)
        if m:
            name = m.group(3).lower()
            if name not in self._tables:
                if m.group(2):
                    return pd.DataFrame(
                        {"status": [f"{name} does not exist, skipped."]})
                raise ValueError(f"table {name!r} does not exist")
            del self._tables[name]
            return pd.DataFrame(
                {"status": [f"{name} successfully dropped."]})

        m = re.match(r"(DESCRIBE|DESC)\s+(TABLE\s+)?(\w+)$", q,
                     re.IGNORECASE)
        if m:
            name = m.group(3).lower()
            if name not in self._tables:
                raise ValueError(f"table {name!r} does not exist")
            schema = self._tables[name].schema
            return pd.DataFrame({"name": list(schema),
                                 "type": [t.name for t in schema.values()]})

        if re.match(r"SHOW\s+TABLES$", up):
            return pd.DataFrame({"name": sorted(self._tables)})
        return None

    def generate_plan(self, query: str):
        """Return the optimized logical plan (EXPLAIN analogue)."""
        from bodo_tpu.plan.optimizer import optimize
        ast = parse_sql(query)
        plan, _ = Planner(self._tables).plan(ast)
        return optimize(plan)

    def explain(self, query: str) -> str:
        """Pretty-printed optimized plan."""
        lines = []

        def walk(n, d):
            lines.append("  " * d + repr(n))
            for c in n.children:
                walk(c, d + 1)
        walk(self.generate_plan(query), 0)
        return "\n".join(lines)

    def explain_analyze(self, query: str) -> str:
        """Plan, EXECUTE, and render the plan tree annotated with the
        observed per-node rows/bytes/wall/AQE decisions (requires
        tracing: set_config(tracing_level=1))."""
        from bodo_tpu.plan import explain
        from bodo_tpu.plan.physical import execute
        from bodo_tpu.utils import tracing
        plan = self.generate_plan(query)
        with tracing.query_span() as qid:
            execute(plan, optimize_first=False)
        return explain.explain_analyze(qid)
