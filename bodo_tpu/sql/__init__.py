"""SQL surface: BodoSQLContext analogue (reference
BodoSQL/bodosql/context.py:111 BodoSQLContext, :504 sql())."""

from __future__ import annotations

from typing import Dict, Optional

import pandas as pd

from bodo_tpu.plan import logical as L
from bodo_tpu.sql.parser import parse_sql
from bodo_tpu.sql.planner import Planner

__all__ = ["BodoSQLContext"]


class BodoSQLContext:
    """Register tables (pandas frames, lazy frames, or parquet paths) and
    run SQL against them. Queries lower to the same logical plan /
    executor as the dataframe frontend."""

    def __init__(self, tables: Optional[Dict] = None):
        self._tables: Dict[str, L.Node] = {}
        for name, t in (tables or {}).items():
            self.add_table(name, t)

    def add_table(self, name: str, table) -> None:
        from bodo_tpu.pandas_api.frame import BodoDataFrame
        if isinstance(table, BodoDataFrame):
            self._tables[name] = table._plan
        elif isinstance(table, pd.DataFrame):
            self._tables[name] = L.FromPandas(table)
        elif isinstance(table, str):
            self._tables[name] = L.ReadParquet(table)
        elif isinstance(table, L.Node):
            self._tables[name] = table
        else:
            raise TypeError(f"cannot register table {name}: {type(table)}")

    def remove_table(self, name: str) -> None:
        del self._tables[name]

    def sql(self, query: str):
        """Plan + execute; returns a lazy BodoDataFrame."""
        from bodo_tpu.pandas_api.frame import BodoDataFrame
        ast = parse_sql(query)
        plan, names = Planner(self._tables).plan(ast)
        return BodoDataFrame(plan)

    def generate_plan(self, query: str):
        """Return the optimized logical plan (EXPLAIN analogue)."""
        from bodo_tpu.plan.optimizer import optimize
        ast = parse_sql(query)
        plan, _ = Planner(self._tables).plan(ast)
        return optimize(plan)
