"""SQL surface: BodoSQLContext analogue (reference
BodoSQL/bodosql/context.py:111 BodoSQLContext, :504 sql())."""

from __future__ import annotations

from typing import Dict, Optional

import pandas as pd

from bodo_tpu.plan import logical as L
from bodo_tpu.sql.parser import parse_sql
from bodo_tpu.sql.planner import Planner

__all__ = ["BodoSQLContext"]


class BodoSQLContext:
    """Register tables (pandas frames, lazy frames, or parquet paths) and
    run SQL against them. Queries lower to the same logical plan /
    executor as the dataframe frontend."""

    def __init__(self, tables: Optional[Dict] = None):
        self._tables: Dict[str, L.Node] = {}
        for name, t in (tables or {}).items():
            self.add_table(name, t)

    def add_table(self, name: str, table) -> None:
        from bodo_tpu.pandas_api.frame import BodoDataFrame
        if isinstance(table, BodoDataFrame):
            self._tables[name] = table._plan
        elif isinstance(table, pd.DataFrame):
            self._tables[name] = L.FromPandas(table)
        elif isinstance(table, str):
            self._tables[name] = L.ReadParquet(table)
        elif isinstance(table, L.Node):
            self._tables[name] = table
        else:
            raise TypeError(f"cannot register table {name}: {type(table)}")

    def remove_table(self, name: str) -> None:
        del self._tables[name]

    def _schema_sig(self) -> str:
        return repr(sorted((n, tuple(p.schema)) for n, p in
                           self._tables.items()))

    def sql(self, query: str):
        """Plan + execute; returns a lazy BodoDataFrame."""
        from bodo_tpu.pandas_api.frame import BodoDataFrame
        from bodo_tpu.sql import plan_cache
        sig = self._schema_sig()
        ast = plan_cache.get(query, sig)
        if ast is None:
            ast = parse_sql(query)
            # pickle to disk BEFORE planning — the planner rewrites AST
            # nodes in place, so only cache-served objects need copying
            plan_cache.put(query, sig, ast)
        else:
            import copy
            ast = copy.deepcopy(ast)
        plan, names = Planner(self._tables).plan(ast)
        return BodoDataFrame(plan)

    def generate_plan(self, query: str):
        """Return the optimized logical plan (EXPLAIN analogue)."""
        from bodo_tpu.plan.optimizer import optimize
        ast = parse_sql(query)
        plan, _ = Planner(self._tables).plan(ast)
        return optimize(plan)

    def explain(self, query: str) -> str:
        """Pretty-printed optimized plan."""
        lines = []

        def walk(n, d):
            lines.append("  " * d + repr(n))
            for c in n.children:
                walk(c, d + 1)
        walk(self.generate_plan(query), 0)
        return "\n".join(lines)
