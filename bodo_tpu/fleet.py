"""bodo_tpu.fleet — fleet serving: one controller, many gangs.

Thin façade over ``runtime/fleet.py``: a single controller in this
process spawns N **gang processes** (each a full PR 14 serving stack —
scheduler, result cache, telemetry endpoint) and multiplexes logical
sessions over them. Queries route to gangs by consistent hashing of
the plan/routing key so repeat traffic lands on a warm result cache;
the controller scrapes every gang's ``/metrics`` + ``/healthz`` and
routes around shed/degraded/dead gangs with the same typed
backpressure contract as single-gang serving. On a cache miss the
owning gang peers with the key's previous owner before recomputing,
and dataset mutations broadcast invalidations fleet-wide.

    import bodo_tpu.fleet as fleet
    ctl = fleet.start(gangs=4)
    s = fleet.session("tenant-a", priority=2.0, slo="latency")
    fut = s.submit(lambda: run_query())     # returns a host value
    try:
        out = fut.result()
    except fleet.Overloaded as e:
        time.sleep(e.retry_after_s)         # typed backpressure
    fleet.stop()

Thunks submitted through the fleet execute in a gang process and their
return value crosses a process boundary — return HOST values (pandas
DataFrames, scalars, lists), not device-resident Tables.

Knobs: ``BODO_TPU_FLEET_*`` (see config.py) — gang count, scrape
cadence, frame-size bound, peering toggle, per-session quota, dead
threshold, optional client-listener port.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from bodo_tpu.runtime.fleet import (  # noqa: F401 - public re-exports
    BackOff,
    Degraded,
    FleetController,
    FleetSession,
    Overloaded,
    ProtocolError,
    QueryFailed,
    RemoteFleet,
    ServeRejection,
    connect,
    controller,
    controller_stats,
    gang_main,
)
from bodo_tpu.runtime import fleet as _impl

__all__ = [
    "start", "stop", "session", "submit", "stats", "gang_stats",
    "connect", "controller", "controller_stats",
    "FleetController", "FleetSession", "RemoteFleet",
    "ProtocolError", "ServeRejection", "Overloaded", "Degraded",
    "BackOff", "QueryFailed",
]


def start(gangs: Optional[int] = None, *,
          gang_env: Optional[Dict[int, Dict[str, str]]] = None,
          timeout: float = 120.0) -> FleetController:
    """Spawn the gang processes and start the controller (idempotent
    while a fleet is running). ``gangs`` defaults to
    ``config.fleet_gangs``; ``gang_env`` overlays extra environment
    onto individual gangs by index (e.g. fault injection for chaos
    tests)."""
    return _impl.start(gangs, gang_env=gang_env, timeout=timeout)


def stop() -> None:
    """Shut the fleet down: polite ``shutdown`` op per gang, then
    stdin-close + kill for stragglers."""
    _impl.stop()


def session(session_id: Optional[str] = None, *, priority: float = 1.0,
            slo: str = "throughput",
            allow_degraded: bool = False) -> FleetSession:
    """Open (or re-open) a logical fleet session. ``slo`` is
    ``"latency"`` (aged ``serve_latency_boost``× faster on every gang)
    or ``"throughput"``; ``priority`` is the fair-share weight."""
    ctl = _impl.controller()
    if ctl is None or not ctl._started:
        ctl = _impl.start()
    return ctl.session(session_id, priority=priority, slo=slo,
                       allow_degraded=allow_degraded)


def submit(fn: Callable, session_id: str = "default", *,
           key: Optional[str] = None):
    """One-shot convenience: submit on a named session."""
    return session(session_id).submit(fn, key=key)


def stats() -> Optional[dict]:
    """Controller-level fleet stats (gang states, reroutes, peering,
    invalidations) — None when no fleet is running."""
    return _impl.controller_stats()


def gang_stats(gang_id: str) -> Optional[dict]:
    """A single gang's own scheduler/result-cache counters, fetched
    over the wire."""
    ctl = _impl.controller()
    if ctl is None:
        return None
    return ctl.gang_stats(gang_id)
