"""Rank-aware critical-path analysis over the merged multi-rank trace.

Input is the dict ``spawn.merge_trace_shards`` produces (also written to
flight-recorder bundles as ``trace_merged.json``): one Chrome-trace
timeline with per-rank lanes (``pid`` = rank) whose timestamps are
normalized to the gang origin. The analyzer answers the question the
per-op profile cannot: WHICH chain of spans actually bounds query wall,
and how much of that chain is communication (``comm:*`` spans from
parallel/comm.py) versus compute.

The path is extracted backward-greedily: start from the span that ends
last, then repeatedly hop (across ranks freely — the lanes share one
clock) to the latest-ending span that finished before the current one
began. With the gang executing in SPMD lockstep this recovers the
straggler-bound chain: wherever one rank lagged, its span is the
latest-ending predecessor and the path routes through it.

Straggler attribution uses the per-dispatch ``wait_s`` the comm spans
carry: the rank everyone waits FOR is the one whose own cumulative wait
is SMALLEST (peers burn wait-time at the rendezvous while the straggler
arrives late and proceeds immediately). ``doctor.py`` applies the same
logic to the lockstep arrival stamps when no merged trace is present.

Stdlib-only; a triage tool must load anywhere.
"""

from __future__ import annotations

from typing import Dict, List, Optional

COMM_PREFIX = "comm:"


def _complete_events(trace: dict,
                     query_id: Optional[str] = None) -> List[dict]:
    evs = []
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        if query_id is not None and \
                (ev.get("args") or {}).get("query_id") != query_id:
            continue
        evs.append(ev)
    return evs


def critical_path(trace: dict,
                  query_id: Optional[str] = None) -> Optional[dict]:
    """Extract the rank-aware longest chain for one query (or the whole
    timeline when ``query_id`` is None). Returns None when the trace
    has no complete events for the query."""
    evs = _complete_events(trace, query_id)
    if not evs:
        return None

    def end(ev) -> float:
        return float(ev.get("ts", 0.0)) + float(ev.get("dur", 0.0))

    # tie-break toward the latest START (the most specific/nested span)
    # so the path prefers leaves over the parents that contain them
    def key(ev):
        return (end(ev), float(ev.get("ts", 0.0)))

    cur = max(evs, key=key)
    chain = [cur]
    # zero-duration spans end exactly where they start, so without the
    # visited set a mark event is its own "latest-ending predecessor"
    # and the walk never terminates
    seen = {id(cur)}
    while True:
        t_start = float(cur.get("ts", 0.0))
        preds = [e for e in evs
                 if end(e) <= t_start and id(e) not in seen]
        if not preds:
            break
        cur = max(preds, key=key)
        chain.append(cur)
        seen.add(id(cur))
    chain.reverse()

    comm_us = compute_us = 0.0
    path = []
    for ev in chain:
        name = ev.get("name", "")
        dur = float(ev.get("dur", 0.0))
        is_comm = name.startswith(COMM_PREFIX)
        if is_comm:
            comm_us += dur
        else:
            compute_us += dur
        entry = {"name": name, "rank": int(ev.get("pid", 0)),
                 "ts_us": round(float(ev.get("ts", 0.0)), 3),
                 "dur_us": round(dur, 3),
                 "kind": "comm" if is_comm else "compute"}
        args = ev.get("args") or {}
        if is_comm:
            if args.get("wait_s"):
                entry["wait_s"] = float(args["wait_s"])
            if args.get("site"):
                entry["site"] = args["site"]
        path.append(entry)
    wall_us = end(chain[-1]) - float(chain[0].get("ts", 0.0))
    total = comm_us + compute_us
    return {
        "query_id": query_id,
        "n_events": len(evs),
        "path": path,
        "wall_us": round(wall_us, 3),
        "comm_us": round(comm_us, 3),
        "compute_us": round(compute_us, 3),
        "comm_frac": round(comm_us / total, 4) if total else 0.0,
    }


def straggler(trace: dict) -> Optional[dict]:
    """Attribute arrival skew to a rank from the per-dispatch peer-wait
    the ``comm:*`` spans carry. The suspect is the rank with the
    SMALLEST cumulative wait (its peers did the waiting); attribution
    is only confident when the spread is meaningful."""
    waits: Dict[int, float] = {}
    sites: Dict[str, float] = {}
    for ev in _complete_events(trace):
        name = ev.get("name", "")
        if not name.startswith(COMM_PREFIX):
            continue
        args = ev.get("args") or {}
        w = float(args.get("wait_s") or 0.0)
        rank = int(ev.get("pid", 0))
        waits[rank] = waits.get(rank, 0.0) + w
        if w:
            site = f"{name[len(COMM_PREFIX):]}@" \
                   f"{args.get('site', '<unknown>')}"
            sites[site] = sites.get(site, 0.0) + w
    if len(waits) < 2:
        return None
    lo_rank = min(waits, key=lambda r: (waits[r], r))
    hi_rank = max(waits, key=lambda r: (waits[r], -r))
    skew = waits[hi_rank] - waits[lo_rank]
    out = {
        "rank_wait_s": {str(r): round(w, 6)
                        for r, w in sorted(waits.items())},
        "straggler_rank": lo_rank,
        "skew_s": round(skew, 6),
        # confident: the straggler's peers each waited noticeably more
        # than it did (10ms floor keeps scheduler jitter out)
        "confident": skew > 0.01
        and waits[hi_rank] > 2.0 * max(waits[lo_rank], 1e-9),
    }
    if sites:
        dom = max(sites, key=lambda s: (sites[s], s))
        out["dominant_site"] = dom
        out["dominant_site_wait_s"] = round(sites[dom], 6)
    return out


def analyze(trace: dict) -> dict:
    """Whole-trace verdict: per-query critical paths + straggler
    attribution + a per-op comm roll-up. ``doctor`` embeds this when a
    bundle carries a merged trace."""
    queries = {}
    for qid in trace.get("query_ids", []) or []:
        cp = critical_path(trace, qid)
        if cp is not None:
            queries[qid] = cp
    overall = critical_path(trace)
    comm_ops: Dict[str, dict] = {}
    for ev in _complete_events(trace):
        name = ev.get("name", "")
        if not name.startswith(COMM_PREFIX):
            continue
        args = ev.get("args") or {}
        r = comm_ops.setdefault(name[len(COMM_PREFIX):], {
            "count": 0, "bytes_in": 0, "bytes_out": 0,
            "wall_us": 0.0, "wait_s": 0.0})
        r["count"] += 1
        r["bytes_in"] += int(args.get("bytes_in") or 0)
        r["bytes_out"] += int(args.get("bytes_out") or 0)
        r["wall_us"] += float(ev.get("dur", 0.0))
        r["wait_s"] += float(args.get("wait_s") or 0.0)
    return {
        "ranks": trace.get("ranks", []),
        "queries": queries,
        "overall": overall,
        "straggler": straggler(trace),
        "comm_ops": comm_ops,
    }
