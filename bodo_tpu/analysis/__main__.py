"""`python -m bodo_tpu.analysis` — the shardcheck CLI.

Default mode runs the stdlib-only lint over the package (exit 0 when
every finding is inline-suppressed or baselined; exit 1 on any new
finding — the `runtests.py lint` CI gate, which also fails on DEAD
baseline entries; `--prune-baseline` rewrites the baseline without
them).

`--programs` switches to the progcheck self-check: trace a
representative program per family, extract collective manifests, and
exit 1 on any invariant violation (the `runtests.py progcheck` gate).
"""

import sys

argv = sys.argv[1:]
if "--programs" in argv:
    from bodo_tpu.analysis import progcheck

    sys.exit(progcheck.main([a for a in argv if a != "--programs"]))

from bodo_tpu.analysis import lint

sys.exit(lint.main(argv))
