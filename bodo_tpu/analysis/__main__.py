"""`python -m bodo_tpu.analysis` — run the shardcheck lint CLI.

Exit 0 when every finding is inline-suppressed or baselined; exit 1 on
any new finding (the `runtests.py lint` CI gate)."""

import sys

from bodo_tpu.analysis import lint

sys.exit(lint.main(sys.argv[1:]))
