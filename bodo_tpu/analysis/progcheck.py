"""progcheck (shardcheck layer 3) — jaxpr-level SPMD program verifier.

shardcheck's AST lint (analysis/lint.py) sees source; the lockstep
checker (analysis/lockstep.py) sees dispatches after they happen. This
module sits between them: every program the engine registers with the
program registry (bounded_jit, the fusion/decode program caches,
cached_builder products, the relational dispatchers) is traced to its
jaxpr and verified BEFORE it can wedge or corrupt a gang. Three passes
per program:

  static lockstep
      Extract the ordered collective primitives (all_to_all,
      all_gather, psum, ppermute, ...) with axis/shape/dtype facets
      into a per-program collective manifest; verify the schedule is
      rank-invariant — no collective under value-dependent control
      flow (cond/while) whose predicate derives from `axis_index`.
      Manifests are registered with analysis/lockstep so a gang's
      program set can be pre-validated before first dispatch, and
      cross-checked against the in-program collectives fused groups
      declare (`register_fusion_manifest(..., in_program=(...))`).

  donation / aliasing audit
      For every `donate_argnums` program, prove no donated input
      escapes to an output through an alias-only chain (reshape /
      transpose / squeeze / expand_dims) — a donated buffer aliased
      into a cached output is read after XLA reuses its pages — and
      that every donated input is actually consumed. Program families
      that cache their outputs across dispatches (the join-build LUT)
      register with `forbid_donation=True`, turning the "never donate
      the build side" comment into a checked contract.

  static HBM peak estimation
      A liveness sweep over the jaxpr computing peak live bytes
      (inputs + outputs + maximal concurrent intermediates,
      donation-aware: a donated input dies at its last use). The
      estimate is recorded per program in the observatory, charged by
      the memory governor before dispatch (preadmission_charge) and
      read by the serve admission controller to shed before trace.

Violations are typed `ProgramInvariantError`s naming the program and
the offending eqn path. `BODO_TPU_PROGCHECK` (default on) gates the
checks; `BODO_TPU_PROGCHECK_ENFORCE` turns warn-and-record into
raise-at-registration.

Module level stays stdlib-only (jax is imported inside functions) so
metrics/tracing/doctor can read `stats()` through the lazy-module rule
without dragging in a backend.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from bodo_tpu.config import config

__all__ = [
    "ProgramInvariantError", "check_jit", "check_jaxpr", "wrap_program",
    "manifests", "manifest_for", "reports", "violations", "stats",
    "hbm_estimate", "max_hbm_estimate", "reset", "main",
]


class ProgramInvariantError(RuntimeError):
    """A statically-provable SPMD invariant violation in a registered
    program: rule, program name and the offending eqn path ride on the
    exception (doctor and the CLI render them)."""

    def __init__(self, program: str, rule: str, message: str,
                 eqn_path: str = ""):
        self.program = program
        self.rule = rule
        self.eqn_path = eqn_path
        where = f" (at {eqn_path})" if eqn_path else ""
        super().__init__(
            f"progcheck[{rule}] program {program!r}: {message}{where}")


# collective primitives whose dispatch order IS the gang's lockstep
# schedule (jax.lax level — what jaxprs contain after tracing)
_COLLECTIVE_PRIMS = {
    "all_to_all", "all_gather", "psum", "pmax", "pmin", "ppermute",
    "pshuffle", "psum_scatter", "reduce_scatter", "all_reduce",
    "pbroadcast",
}

# primitives that alias (or may alias) their operand's buffer rather
# than copying — a donated input reaching an output through ONLY these
# means the "output" is the donated buffer itself
_ALIAS_PRIMS = {"reshape", "transpose", "squeeze", "expand_dims",
                "rev", "copy"}

# control-flow primitives whose predicate selects which eqns run
_BRANCHY_PRIMS = {"cond", "while"}

_mu = threading.RLock()
_reports: Dict[str, dict] = {}          # program -> report
_checked_handles: set = set()           # observatory handles verified
_warned: set = set()                    # programs already warn-logged
_stats = {
    "programs": 0,          # programs verified
    "violations": 0,        # violations recorded (warn or enforce)
    "skipped": 0,           # trace failures / disabled at call time
    "check_s": 0.0,         # total verification wall
    "max_check_s": 0.0,     # slowest single verification
    "manifests": 0,         # collective manifests registered
}


def enabled() -> bool:
    return bool(config.progcheck)


def enforcing() -> bool:
    return bool(config.progcheck_enforce)


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _src(eqn) -> str:
    """`file.py:line` of the eqn's user frame ("" when unavailable)."""
    try:
        from jax._src import source_info_util
        fr = source_info_util.user_frame(eqn.source_info)
        if fr is not None:
            import os
            return f"{os.path.basename(fr.file_name)}:{fr.start_line}"
    except Exception:  # noqa: BLE001 - source info is best-effort
        pass
    return ""


def _sub_jaxprs(params: dict) -> List[Tuple[str, Any]]:
    """(param_key, jax.core.Jaxpr) for every sub-jaxpr hiding in an
    eqn's params (jaxpr / closed jaxpr / tuples of either)."""
    import jax
    out: List[Tuple[str, Any]] = []

    def _coerce(v):
        if isinstance(v, jax.core.ClosedJaxpr):
            return v.jaxpr
        if isinstance(v, jax.core.Jaxpr):
            return v
        return None

    for k, v in params.items():
        j = _coerce(v)
        if j is not None:
            out.append((k, j))
        elif isinstance(v, (tuple, list)):
            for i, item in enumerate(v):
                j = _coerce(item)
                if j is not None:
                    out.append((f"{k}[{i}]", j))
    return out


def _aval_bytes(aval) -> int:
    try:
        return int(aval.size) * int(aval.dtype.itemsize)
    except Exception:  # noqa: BLE001 - abstract tokens etc.
        return 0


def _is_literal(v) -> bool:
    import jax
    return isinstance(v, jax.core.Literal)


def _collective_facets(eqn, path: str) -> dict:
    p = eqn.params
    axis = p.get("axis_name", p.get("axes", p.get("axis_index_groups")))
    shape = dtype = None
    for ov in eqn.outvars:
        aval = getattr(ov, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            shape, dtype = tuple(aval.shape), str(aval.dtype)
            break
    return {"prim": eqn.primitive.name, "axis": str(axis),
            "shape": shape, "dtype": dtype, "eqn": path,
            "line": _src(eqn)}


def _scan_jaxpr(jaxpr, tainted: set, ambient_divergent: bool,
                path: str, collectives: List[dict],
                violations: List[dict], program: str) -> None:
    """One pass: collect collectives in dispatch order, propagate
    axis-index taint, and flag any collective reachable only through
    control flow whose predicate carries that taint."""
    for i, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        epath = f"{path}eqns[{i}]:{name}"
        in_tainted = any((not _is_literal(v)) and v in tainted
                         for v in eqn.invars)
        if name in _COLLECTIVE_PRIMS:
            collectives.append(_collective_facets(eqn, epath))
            if ambient_divergent:
                violations.append({
                    "rule": "rank-divergent-collective",
                    "program": program, "eqn": epath,
                    "line": _src(eqn),
                    "message": f"collective {name!r} under control flow "
                               f"whose predicate derives from "
                               f"axis_index: ranks where the predicate "
                               f"differs skip the collective and the "
                               f"gang hangs"})
        pred_tainted = False
        if name == "cond":
            pv = eqn.invars[0]
            pred_tainted = (not _is_literal(pv)) and pv in tainted
        elif name == "while":
            # the carry feeds cond_jaxpr: tainted carry => tainted
            # trip count (conservative)
            pred_tainted = in_tainted
        child_divergent = ambient_divergent or \
            (name in _BRANCHY_PRIMS and pred_tainted)
        subs = _sub_jaxprs(eqn.params)
        if subs:
            ops = eqn.invars[1:] if name == "cond" else eqn.invars
            for key, sub in subs:
                sub_tainted: set = set()
                if len(sub.invars) == len(ops):
                    for sv, ov in zip(sub.invars, ops):
                        if (not _is_literal(ov)) and ov in tainted:
                            sub_tainted.add(sv)
                elif in_tainted:
                    # unknown calling convention: taint everything
                    sub_tainted.update(sub.invars)
                _scan_jaxpr(sub, sub_tainted, child_divergent,
                            f"{epath}/{key}/", collectives, violations,
                            program)
        if name == "axis_index" or in_tainted:
            tainted.update(eqn.outvars)


def _peak_live_bytes(jaxpr, donated: set) -> int:
    """Delta-sweep liveness: peak concurrent bytes across eqn steps.
    Non-donated inputs and constvars live for the whole program;
    donated inputs die at their last contributing use; every value
    feeding a program output lives to the end. Sub-jaxprs contribute
    max(0, sub_peak - sub_io) as transient extra at their eqn."""
    n = len(jaxpr.eqns)
    last_use: Dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[v] = i
    birth: Dict[Any, int] = {}
    death: Dict[Any, int] = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        birth[v] = 0
        death[v] = last_use.get(v, 0) if v in donated else n
    for i, eqn in enumerate(jaxpr.eqns):
        for o in eqn.outvars:
            birth[o] = i
            death[o] = last_use.get(o, i)
    for v in jaxpr.outvars:
        if not _is_literal(v):
            death[v] = n
    deltas = [0] * (n + 2)
    for v, b in birth.items():
        nb = _aval_bytes(getattr(v, "aval", None))
        if nb <= 0:
            continue
        deltas[b] += nb
        deltas[death.get(v, b) + 1] -= nb
    extras = [0] * (n + 1)
    for i, eqn in enumerate(jaxpr.eqns):
        for _, sub in _sub_jaxprs(eqn.params):
            sub_peak = _peak_live_bytes(sub, set())
            io = sum(_aval_bytes(getattr(v, "aval", None))
                     for v in list(sub.invars) + list(sub.outvars)
                     if not _is_literal(v))
            extras[i] += max(0, sub_peak - io)
    peak = running = 0
    for i in range(n + 1):
        running += deltas[i]
        peak = max(peak, running + (extras[i] if i < n else 0))
    return peak


def _audit_donation(jaxpr, donated: set, program: str,
                    forbid_donation: bool,
                    violations: List[dict]) -> None:
    if not donated:
        return
    if forbid_donation:
        idxs = sorted(i for i, v in enumerate(jaxpr.invars)
                      if v in donated)
        violations.append({
            "rule": "forbidden-donation", "program": program,
            "eqn": f"invars{idxs}", "line": "",
            "message": f"program family registers with "
                       f"forbid_donation=True (outputs are cached "
                       f"across dispatches) but donates inputs "
                       f"{idxs}: a later dispatch would read pages "
                       f"XLA already reused"})
    used: set = set()
    for eqn in jaxpr.eqns:
        used.update(v for v in eqn.invars if not _is_literal(v))
    used.update(v for v in jaxpr.outvars if not _is_literal(v))
    # alias-only reachability from each donated input to an output
    alias_of: Dict[Any, Any] = {v: v for v in donated}
    for i, eqn in enumerate(jaxpr.eqns):
        if eqn.primitive.name in _ALIAS_PRIMS and eqn.invars and \
                not _is_literal(eqn.invars[0]) and \
                eqn.invars[0] in alias_of:
            for o in eqn.outvars:
                alias_of[o] = alias_of[eqn.invars[0]]
    out_set = {v for v in jaxpr.outvars if not _is_literal(v)}
    for i, v in enumerate(jaxpr.invars):
        if v not in donated:
            continue
        if v not in used:
            violations.append({
                "rule": "unused-donation", "program": program,
                "eqn": f"invars[{i}]", "line": "",
                "message": f"donated input {i} is never consumed: the "
                           f"donation frees nothing and masks a stale "
                           f"donate_argnums"})
        hit = next((o for o in out_set
                    if alias_of.get(o) is v), None)
        if hit is not None:
            oi = next(j for j, o in enumerate(jaxpr.outvars)
                      if o is hit)
            violations.append({
                "rule": "read-after-donation", "program": program,
                "eqn": f"invars[{i}]->outvars[{oi}]", "line": "",
                "message": f"donated input {i} reaches output {oi} "
                           f"through an alias-only chain: the caller "
                           f"holds (or caches) a view of a buffer XLA "
                           f"is free to reuse — reading it after "
                           f"dispatch is use-after-free"})


# ---------------------------------------------------------------------------
# verification entry points
# ---------------------------------------------------------------------------

def check_jaxpr(closed, *, program: str, subsystem: str = "",
                donated_argnums: Tuple[int, ...] = (),
                declared_collectives: Optional[Tuple[str, ...]] = None,
                forbid_donation: bool = False) -> dict:
    """Run the three passes over one ClosedJaxpr; returns the report
    (never raises — enforcement is the caller's job)."""
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    donated = {jaxpr.invars[i] for i in donated_argnums
               if 0 <= i < len(jaxpr.invars)}
    collectives: List[dict] = []
    viols: List[dict] = []
    _scan_jaxpr(jaxpr, set(), False, "", collectives, viols, program)
    _audit_donation(jaxpr, donated, program, forbid_donation, viols)
    if declared_collectives is not None:
        got = {c["prim"] for c in collectives}
        want = set(declared_collectives)
        # subset, not equality: incidental collectives (count gathers
        # inside a shuffle helper) extract into the manifest without
        # being declared — only a DECLARED collective missing from the
        # traced program is a lie the lockstep checker would act on
        if not want <= got:
            viols.append({
                "rule": "manifest-mismatch", "program": program,
                "eqn": "", "line": "",
                "message": f"fused group declares in-program "
                           f"collectives {sorted(want)} but the traced "
                           f"program contains {sorted(got)}: the "
                           f"lockstep pre-validation manifest would "
                           f"lie to the runtime checker"})
    return {
        "program": program,
        "subsystem": subsystem,
        "collectives": collectives,
        "rank_invariant": not any(v["rule"] == "rank-divergent-collective"
                                  for v in viols),
        "violations": viols,
        "hbm_bytes": int(_peak_live_bytes(jaxpr, donated)),
        "donated": len(donated),
        "declared": list(declared_collectives)
        if declared_collectives is not None else None,
    }


def _record(report: dict, obs_handle: int, check_s: float,
            enforce: Optional[bool]) -> dict:
    program = report["program"]
    report["check_s"] = check_s
    report["obs_handle"] = obs_handle
    with _mu:
        _stats["programs"] += 1
        _stats["violations"] += len(report["violations"])
        _stats["check_s"] += check_s
        _stats["max_check_s"] = max(_stats["max_check_s"], check_s)
        _stats["manifests"] += 1
        _reports[program] = report
        if obs_handle:
            _checked_handles.add(obs_handle)
        warn_new = program not in _warned
        _warned.add(program)
    # lockstep pre-validation manifest (collective prim order)
    try:
        from bodo_tpu.analysis import lockstep
        lockstep.register_program_manifest(
            program,
            collectives=tuple(c["prim"] for c in report["collectives"]),
            rank_invariant=report["rank_invariant"],
            subsystem=report["subsystem"],
            hbm_bytes=report["hbm_bytes"],
            violations=len(report["violations"]))
    except Exception:  # noqa: BLE001 - manifest registry best-effort
        pass
    # observatory: per-program row -> registry dumps -> flight bundles
    obs = sys.modules.get("bodo_tpu.runtime.xla_observatory")
    if obs is not None and obs_handle:
        try:
            obs.note_progcheck(obs_handle, {
                "collectives": [c["prim"]
                                for c in report["collectives"]],
                "rank_invariant": report["rank_invariant"],
                "hbm_bytes": report["hbm_bytes"],
                "violations": [
                    {"rule": v["rule"], "eqn": v["eqn"],
                     "line": v["line"]}
                    for v in report["violations"]],
            })
        except Exception:  # noqa: BLE001
            pass
    if report["violations"]:
        v0 = report["violations"][0]
        do_enforce = enforcing() if enforce is None else enforce
        if do_enforce:
            raise ProgramInvariantError(program, v0["rule"],
                                        v0["message"], v0["eqn"])
        if warn_new:
            from bodo_tpu.utils.logging import log
            log(1, f"progcheck: program {program!r}: "
                   f"{len(report['violations'])} violation(s), first: "
                   f"[{v0['rule']}] {v0['message']} (at {v0['eqn']}) "
                   f"— set BODO_TPU_PROGCHECK_ENFORCE=1 to reject at "
                   f"registration")
    return report


def check_jit(fn, args: tuple = (), kwargs: Optional[dict] = None, *,
              program: str, subsystem: str = "",
              declared_collectives: Optional[Tuple[str, ...]] = None,
              forbid_donation: bool = False, obs_handle: int = 0,
              enforce: Optional[bool] = None) -> Optional[dict]:
    """Trace a jitted callable with the given call args and verify it.
    Returns the report, or None when disabled / already verified /
    untraceable. Raises ProgramInvariantError only in enforce mode."""
    if not enabled():
        return None
    with _mu:
        if obs_handle and obs_handle in _checked_handles:
            return _reports.get(program)
        if not obs_handle and program in _reports:
            return _reports[program]
    t0 = time.perf_counter()
    try:
        traced = fn.trace(*args, **(kwargs or {}))
        closed = traced.jaxpr
        import jax
        leaves = jax.tree_util.tree_leaves(traced.args_info)
        donated_argnums = tuple(
            i for i, lf in enumerate(leaves)
            if bool(getattr(lf, "donated", False)))
    except ProgramInvariantError:
        raise
    except Exception:  # noqa: BLE001 - never break dispatch on a
        with _mu:      # trace we cannot reproduce statically
            _stats["skipped"] += 1
        return None
    report = check_jaxpr(
        closed, program=program, subsystem=subsystem,
        donated_argnums=donated_argnums,
        declared_collectives=declared_collectives,
        forbid_donation=forbid_donation)
    return _record(report, obs_handle, time.perf_counter() - t0,
                   enforce)


def mark_checked(handle: int) -> None:
    """Skip-list an observatory handle whose program was already
    verified under another name (e.g. fusion checks `fused:<fp>`
    explicitly before the FusionProgramCache store wraps the same
    executable under its cache handle)."""
    if handle:
        with _mu:
            _checked_handles.add(handle)


class _CheckedProgram:
    """Transparent callable proxy: verifies the wrapped program on its
    first dispatch (when real call args exist to trace against), then
    delegates forever. Attribute access falls through to the program,
    so `.lower`, `.trace`, jit internals all keep working."""

    __slots__ = ("_fn", "_ck", "_done", "__weakref__")

    def __init__(self, fn, ck: dict):
        self._fn = fn
        self._ck = ck
        self._done = False

    def __call__(self, *args, **kwargs):
        if not self._done and enabled():
            self._done = True
            check_jit(self._fn, args, kwargs, **self._ck)
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self):
        return f"progcheck({self._fn!r})"


def wrap_program(fn, *, program: str, subsystem: str = "",
                 declared_collectives=None, forbid_donation: bool = False,
                 obs_handle: int = 0):
    """Wrap a jitted callable for first-dispatch verification. Returns
    ``fn`` unchanged when it isn't traceable (no `.trace`) or is
    already wrapped."""
    if isinstance(fn, _CheckedProgram) or not hasattr(fn, "trace") \
            or not callable(fn):
        return fn
    return _CheckedProgram(fn, dict(
        program=program, subsystem=subsystem,
        declared_collectives=declared_collectives,
        forbid_donation=forbid_donation, obs_handle=obs_handle))


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------

def reports() -> Dict[str, dict]:
    with _mu:
        return {k: dict(v) for k, v in _reports.items()}


def manifests() -> Dict[str, list]:
    with _mu:
        return {k: list(v["collectives"]) for k, v in _reports.items()}


def manifest_for(program: str) -> Optional[list]:
    with _mu:
        r = _reports.get(program)
        return list(r["collectives"]) if r is not None else None


def violations() -> List[dict]:
    with _mu:
        return [dict(v) for r in _reports.values()
                for v in r["violations"]]


def hbm_estimate(program: str) -> Optional[int]:
    with _mu:
        r = _reports.get(program)
        return int(r["hbm_bytes"]) if r is not None else None


def max_hbm_estimate() -> int:
    with _mu:
        return max((int(r["hbm_bytes"]) for r in _reports.values()),
                   default=0)


def stats() -> dict:
    with _mu:
        out = dict(_stats)
        out["hbm_peak_bytes_max"] = max(
            (int(r["hbm_bytes"]) for r in _reports.values()), default=0)
        out["rank_variant_programs"] = sum(
            1 for r in _reports.values() if not r["rank_invariant"])
    out["enforce"] = 1 if enforcing() else 0
    return out


def reset() -> None:
    with _mu:
        _reports.clear()
        _checked_handles.clear()
        _warned.clear()
        for k in _stats:
            _stats[k] = 0.0 if k in ("check_s", "max_check_s") else 0
    ls = sys.modules.get("bodo_tpu.analysis.lockstep")
    if ls is not None:
        ls.clear_program_manifests()


# ---------------------------------------------------------------------------
# CLI: `python -m bodo_tpu.analysis --programs`
# ---------------------------------------------------------------------------

def _self_check_programs():
    """Representative tiny programs, one per verification concern —
    traced fresh in this process so the CLI is meaningful without a
    prior workload."""
    import jax
    import jax.numpy as jnp

    # throwaway CLI-only programs: never dispatched, never cached —
    # the registry bypass is the point (we verify them directly)
    progs = []
    progs.append(("selfcheck:elementwise",
                  jax.jit(lambda x: x * 2 + 1),  # shardcheck: ignore[unregistered-jit]
                  (jnp.arange(8, dtype=jnp.float32),), {}))
    progs.append(("selfcheck:donated",
                  jax.jit(lambda x: jnp.cumsum(x), donate_argnums=(0,)),  # shardcheck: ignore[unregistered-jit]
                  (jnp.arange(8, dtype=jnp.float32),), {}))

    devs = jax.devices()
    try:
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        mesh = Mesh(devs[:1], ("x",))

        def body(x):
            # traced, never dispatched: the enclosing try guards mesh
            # construction on meshless backends, not the dispatch
            return jax.lax.psum(x, "x")  # shardcheck: ignore[swallowed-collective]

        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(),),  # shardcheck: ignore[unregistered-jit]
                               out_specs=P(), check_rep=False))
        progs.append(("selfcheck:collective", fn,
                      (jnp.arange(4, dtype=jnp.float32),), {}))
    except Exception:  # noqa: BLE001 - no mesh on this backend
        pass
    return progs


def main(argv: Optional[List[str]] = None) -> int:
    """`--programs` CLI mode: verify the self-check program set (plus
    anything already registered in this process) and print manifests;
    exit 1 on any violation."""
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m bodo_tpu.analysis --programs",
        description="progcheck: jaxpr-level SPMD program verification")
    ap.add_argument("--programs", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report dump")
    ap.add_argument("--enforce", action="store_true",
                    help="raise on first violation instead of listing")
    ap.parse_known_args(argv)
    args = ap.parse_args(argv)

    for name, fn, a, kw in _self_check_programs():
        check_jit(fn, a, kw, program=name, subsystem="selfcheck",
                  enforce=args.enforce)
    reps = reports()
    if args.json:
        print(json.dumps(reps, indent=1, sort_keys=True, default=str))
    else:
        for name in sorted(reps):
            r = reps[name]
            sched = " -> ".join(c["prim"] for c in r["collectives"]) \
                or "(no collectives)"
            flag = "RANK-VARIANT" if not r["rank_invariant"] else "ok"
            print(f"{name}: {sched} | hbm~{r['hbm_bytes']}B | "
                  f"donated={r['donated']} | {flag}")
            for v in r["violations"]:
                print(f"  VIOLATION [{v['rule']}] {v['message']} "
                      f"(at {v['eqn']})")
    bad = violations()
    print(f"progcheck: {len(reps)} programs, {len(bad)} violations")
    return 1 if bad else 0
