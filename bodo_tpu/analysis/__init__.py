"""shardcheck — the SPMD safety analyzer, three layers:

  plan_validator   distribution/shape typing over the logical plan DAG
                   (runs automatically before execution;
                   `validate_plan` is the explicit API)
  lint             stdlib-ast rules over the codebase itself
                   (`python -m bodo_tpu.analysis`)
  lockstep         runtime collective-dispatch lockstep checker
                   (debug mode, BODO_TPU_LOCKSTEP=1)
  progcheck        jaxpr-level SPMD program verifier at registration
                   points: static lockstep manifests, donation audit,
                   pre-dispatch HBM peak estimation
                   (`python -m bodo_tpu.analysis --programs`)

Submodules import lazily: `lockstep` and `progcheck` are on the hot
dispatch/registration paths and must not drag the plan layer in, and
`plan_validator` pulls plan.expr (jax) which the stdlib-only lint CLI
path defers as long as possible.
"""

from __future__ import annotations

_LAZY = ("plan_validator", "lint", "lockstep", "progcheck")

__all__ = ["PlanInvariantError", "LockstepError",
           "ProgramInvariantError", "validate_plan", "dist_of", *_LAZY]


def __getattr__(name):
    import importlib
    if name in _LAZY:
        return importlib.import_module(f"{__name__}.{name}")
    if name in ("PlanInvariantError", "validate_plan", "dist_of"):
        mod = importlib.import_module(f"{__name__}.plan_validator")
        return getattr(mod, name)
    if name == "LockstepError":
        from bodo_tpu.analysis.lockstep import LockstepError
        return LockstepError
    if name == "ProgramInvariantError":
        from bodo_tpu.analysis.progcheck import ProgramInvariantError
        return ProgramInvariantError
    raise AttributeError(name)
