"""Runtime SPMD lockstep checker (shardcheck layer 3).

Debug mode (`BODO_TPU_LOCKSTEP=1` / `set_config(lockstep=True)`): every
host-level collective dispatch in relational.py's dispatchers
(`_inject_collective`, the PR-2 fault-injection plumbing) is
fingerprinted as `op@file:line` and assigned a per-process sequence
number. Each process appends its (seq, fingerprint) stream to an
append-only side-channel file in the gang's shared temp directory (the
same directory that carries the spawn heartbeats), and cross-checks its
peers' streams before proceeding:

  * a peer that dispatched a DIFFERENT collective at the same sequence
    number -> immediate :class:`LockstepError` naming both ranks and
    both call sites (divergent control flow through a gang-scheduled
    op — the Pathways failure class that otherwise hangs the gang);
  * a peer that has NOT reached this sequence number within
    `config.lockstep_timeout_s` -> :class:`LockstepError` naming the
    lagging rank and its last-seen dispatch (a skipped collective or a
    wedged process), in seconds instead of the 180s gang timeout.

Single-process runs (or runs without a shared directory) still count
and fingerprint dispatches — that is what the bench.py overhead suite
measures — but have no peers to check.

The checker is ~free when disabled: one config attribute read per
dispatch. spawn.py exports BODO_TPU_LOCKSTEP_DIR pointing at each
gang's fresh temp dir so seq numbers never collide with a previous
gang's logs.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, Optional

from bodo_tpu.config import config

_POLL_S = 0.02


class LockstepError(RuntimeError):
    """SPMD lockstep violation: a rank diverged at a host-level
    collective dispatch. Carries the sequence number, this rank, the
    peer rank, and both fingerprints (op@file:line).

    NOTE: messages deliberately avoid the resilience layer's transient/
    degradable marker strings — divergence is a correctness bug that
    must surface, never be retried or degraded away (resilience.py
    additionally excludes this class by name)."""

    def __init__(self, message: str, seq: int = 0, rank: int = 0,
                 peer: Optional[int] = None, site: str = "",
                 peer_site: str = ""):
        self.seq = seq
        self.rank = rank
        self.peer = peer
        self.site = site
        self.peer_site = peer_site
        super().__init__(message)


_lock = threading.Lock()
_checker = None       # Checker | False (disabled after warning) | None
_stats = {"collectives": 0, "wait_s": 0.0, "max_wait_s": 0.0,
          "mismatches": 0, "timeouts": 0, "fused_dispatches": 0,
          "prevalidations": 0, "prevalidation_issues": 0}
# mesh epoch: bumped by the elastic layer on every re-mesh (shrink or
# grow). Sequence numbers and fingerprints are namespaced per epoch —
# survivors of a shrink restart from seq 1 in fresh per-epoch logs, so
# post-recovery dispatches can never be cross-checked against the old
# mesh's stream (which would false-positive as divergence).
_mesh_epoch = 0

# Whole-stage fusion moves member collectives INSIDE one compiled
# program, where per-op pre_collective hooks can no longer fire at
# dispatch (they would fire at trace time only). Instead plan/fusion.py
# registers a per-group manifest at compile time (the member op
# fingerprints + collective count the program subsumes) and the group
# dispatch is sequence-numbered as ONE composite collective via
# pre_fused() — peers must dispatch the same group at the same seq.
_manifests: Dict[str, dict] = {}

# Static per-program collective manifests extracted by the jaxpr
# verifier (analysis/progcheck.py) at registration time: program name
# -> ordered collective primitive names + rank-invariance verdict.
# These are what pre_validate_programs() checks BEFORE a gang's first
# dispatch — a rank-variant program is a guaranteed future divergence,
# so it is reported while the gang is still idle and debuggable.
_program_manifests: Dict[str, dict] = {}


def stats() -> dict:
    with _lock:
        return dict(_stats)


def sequence_head() -> int:
    """Sequence number of this process's last fingerprinted collective
    dispatch (0 before any dispatch / when the checker is off). The
    telemetry sampler records it so a wedged gang's bundle shows how
    far each rank got."""
    c = _checker
    if not c:  # None (unbound) or False (disabled)
        return 0
    with c._mu:
        return c.seq


def _flight_record(err: "LockstepError") -> None:
    """Best-effort flight-recorder bundle at the moment of divergence
    (the raise may be swallowed by user code; the bundle survives).
    Lazy: never pulls the telemetry module in just for this."""
    tl = sys.modules.get("bodo_tpu.runtime.telemetry")
    if tl is None:
        try:
            from bodo_tpu.runtime import telemetry as tl
        except Exception:
            return
    try:
        tl.dump_bundle(f"lockstep_seq{err.seq}_rank{err.rank}",
                       gang_dir=config.lockstep_dir or None)
    except Exception:
        pass


def reset() -> None:
    """Drop the active checker and zero counters (tests; also called by
    set_config when any lockstep knob changes so the next dispatch
    rebinds to the new settings)."""
    global _checker, _mesh_epoch
    with _lock:
        if _checker:
            _checker.close()
        _checker = None
        _mesh_epoch = 0
        for k in _stats:
            _stats[k] = 0 if k != "wait_s" and k != "max_wait_s" else 0.0


def mesh_epoch() -> int:
    return _mesh_epoch


def set_mesh_epoch(epoch: int, rank: Optional[int] = None,
                   nprocs: Optional[int] = None) -> None:
    """Enter a new mesh epoch after an elastic re-mesh: drop the
    current checker so the next dispatch rebinds under the (renumbered)
    rank/nprocs the caller has already published to the environment,
    with a fresh sequence counter, an epoch-suffixed log file, and
    epoch-prefixed fingerprints. Cumulative stats are preserved — a
    re-mesh is recovery, not a test reset."""
    global _checker, _mesh_epoch
    with _lock:
        if _checker:
            _checker.close()
        _checker = None
        _mesh_epoch = int(epoch)
    if rank is not None:
        os.environ["BODO_TPU_PROC_ID"] = str(int(rank))
    if nprocs is not None:
        os.environ["BODO_TPU_NPROCS"] = str(int(nprocs))


def _log_name(epoch: int, rank: int) -> str:
    # epoch 0 keeps the historical name: telemetry's log tail, doctor's
    # skew triage and existing gangs all parse lockstep_<rank>.log
    if epoch:
        return f"lockstep_e{epoch}_{rank}.log"
    return f"lockstep_{rank}.log"


def _rank() -> int:
    v = os.environ.get("BODO_TPU_PROC_ID")
    if v not in (None, ""):
        return int(v)
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            return 0
    return 0


def _nprocs() -> int:
    v = os.environ.get("BODO_TPU_NPROCS")
    if v not in (None, ""):
        return int(v)
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_count())
        except Exception:
            return 1
    return 1


_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _call_site() -> str:
    """First stack frame OUTSIDE the bodo_tpu package (the user-level
    call that led to this collective), as basename:lineno — stable
    across ranks regardless of checkout path or cwd."""
    f = sys._getframe(2)
    while f is not None:
        fname = f.f_code.co_filename
        if not fname.startswith(_PKG_DIR):
            return f"{os.path.basename(fname)}:{f.f_lineno}"
        f = f.f_back
    return "<internal>"


def pre_collective(op: str) -> float:
    """Record + cross-check one host-level collective dispatch. Called
    by relational._inject_collective / shuffle_by_key and the streaming
    executors' per-batch steps right before the sharded kernel
    dispatches. Returns the seconds this rank spent waiting for its
    peers to arrive (0.0 without peers or with the checker off) — the
    arrival-skew signal the comm observatory records per dispatch."""
    if not config.lockstep:
        return 0.0
    c = _get_checker()
    if c is None:
        return 0.0
    return c.check(op, _call_site())


def register_fusion_manifest(group_fp: str, ops, collectives: int,
                             in_program=()) -> None:
    """Register the collective manifest of one compiled fusion group:
    the member-op fingerprints the fused program subsumes, how many
    host count syncs a dispatch implies, and — new with the fused-join
    work — the NAMES of the collectives traced INSIDE the compiled body
    (``in_program``, e.g. ``("all_to_all", "psum")``). Those collectives
    never pass through the host dispatch hooks, so the manifest is the
    only record of them: the comm observatory resolves it via
    ``comm.record_in_program`` to attribute bytes/latency, and lockstep
    divergence reports can name what a fused[...] fingerprint subsumes.
    Called at group compile time (once per distinct group signature);
    cheap enough to call unconditionally so manifests exist when
    lockstep is enabled later."""
    with _lock:
        _manifests[group_fp] = {"ops": tuple(ops),
                                "collectives": int(collectives),
                                "in_program": tuple(in_program)}


def fusion_manifest(group_fp: str) -> Optional[dict]:
    with _lock:
        m = _manifests.get(group_fp)
        return dict(m) if m is not None else None


def fusion_manifests() -> Dict[str, dict]:
    with _lock:
        return {k: dict(v) for k, v in _manifests.items()}


def register_program_manifest(program: str, *, collectives=(),
                              rank_invariant: bool = True,
                              subsystem: str = "", hbm_bytes: int = 0,
                              violations: int = 0) -> None:
    """Register the STATIC collective manifest of one verified program
    (called by progcheck at trace time): the ordered collective
    primitive names the compiled body dispatches, whether the schedule
    is provably rank-invariant, and the static HBM peak estimate.
    Unconditional and cheap, like register_fusion_manifest — manifests
    must exist before lockstep is ever enabled."""
    with _lock:
        _program_manifests[program] = {
            "collectives": tuple(collectives),
            "rank_invariant": bool(rank_invariant),
            "subsystem": subsystem,
            "hbm_bytes": int(hbm_bytes),
            "violations": int(violations),
        }


def program_manifest(program: str) -> Optional[dict]:
    with _lock:
        m = _program_manifests.get(program)
        return dict(m) if m is not None else None


def program_manifests() -> Dict[str, dict]:
    with _lock:
        return {k: dict(v) for k, v in _program_manifests.items()}


def clear_program_manifests() -> None:
    with _lock:
        _program_manifests.clear()


def pre_validate_programs() -> list:
    """Validate the gang's registered program set BEFORE first
    dispatch: (1) no program's static manifest is rank-variant (a
    guaranteed divergence once dispatched); (2) every fused group that
    declared in-program collectives agrees with the verifier's
    extracted manifest for its compiled program. Returns the issue
    strings (also counted in stats); called when the checker binds."""
    issues = []
    with _lock:
        progs = {k: dict(v) for k, v in _program_manifests.items()}
        groups = {k: dict(v) for k, v in _manifests.items()}
    for name, m in sorted(progs.items()):
        if not m["rank_invariant"]:
            issues.append(
                f"program {name!r} has a rank-VARIANT collective "
                f"schedule (collectives under axis_index-derived "
                f"control flow): dispatching it will diverge the gang")
    for fp, g in sorted(groups.items()):
        declared = set(g.get("in_program") or ())
        if not declared:
            continue
        pm = progs.get(f"fused:{fp}")
        if pm is None:
            continue
        got = set(pm["collectives"])
        if not declared <= got:
            issues.append(
                f"fused group {fp!r} declares in-program collectives "
                f"{sorted(declared)} but its verified program traced "
                f"only {sorted(got)}: the manifest lies to the "
                f"runtime checker")
    with _lock:
        _stats["prevalidations"] += 1
        _stats["prevalidation_issues"] += len(issues)
    return issues


def pre_fused(group_fp: str) -> float:
    """Sequence-number one fused-group dispatch as a composite
    collective. The fingerprint is the group fp alone (derived from the
    group's structural signature, so identical across ranks even when a
    rank registered its manifest in a different order); the manifest
    resolves the fp back to member ops for diagnostics/profiling.
    Returns the peer-wait seconds like pre_collective."""
    if not config.lockstep:
        return 0.0
    c = _get_checker()
    if c is None:
        return 0.0
    with _lock:
        _stats["fused_dispatches"] += 1
    return c.check(f"fused[{group_fp}]", _call_site())


def _get_checker() -> Optional["Checker"]:
    global _checker
    c = _checker
    if c is not None:
        return c or None  # False sentinel -> disabled
    with _lock:
        if _checker is not None:
            return _checker or None
        d = config.lockstep_dir
        nprocs = _nprocs()
        if nprocs > 1 and not d:
            sys.stderr.write(
                "bodo_tpu.lockstep: BODO_TPU_LOCKSTEP=1 in a multi-"
                "process run but no BODO_TPU_LOCKSTEP_DIR shared "
                "directory; lockstep checking disabled\n")
            _checker = False
            return None
        _checker = Checker(d or None, _rank(), nprocs,
                           epoch=_mesh_epoch)
        c = _checker
    # pre-validate the program set before this gang's FIRST dispatch
    # (outside _lock: pre_validate_programs takes it)
    for issue in pre_validate_programs():
        sys.stderr.write(f"bodo_tpu.lockstep: pre-validation: "
                         f"{issue}\n")
    return c


class _PeerLog:
    """Incremental reader of one peer's append-only dispatch log."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._buf = ""
        self._entries: Dict[int, str] = {}
        self._last = 0

    def _refresh(self) -> None:
        try:
            with open(self.path, "r") as f:
                f.seek(self._pos)
                data = f.read()
                self._pos = f.tell()
        except OSError:
            return
        if not data:
            return
        self._buf += data
        lines = self._buf.split("\n")
        self._buf = lines.pop()  # partial trailing line (if any)
        for line in lines:
            if "\t" not in line:
                continue
            # seq \t fingerprint [\t arrival-ts] — the third field is
            # the wall-clock arrival stamp doctor's skew triage reads;
            # the cross-check compares fingerprints only
            parts = line.split("\t")
            try:
                seq = int(parts[0])
            except ValueError:
                continue
            self._entries[seq] = parts[1]
            self._last = max(self._last, seq)

    def entry(self, seq: int) -> Optional[str]:
        if seq not in self._entries:
            self._refresh()
        return self._entries.get(seq)

    def last(self) -> str:
        self._refresh()
        if not self._last:
            return "nothing (no collective dispatched yet)"
        return f"#{self._last} {self._entries[self._last]}"


class Checker:
    """Per-process lockstep state: own sequence counter + log writer,
    plus incremental readers over every peer's log."""

    def __init__(self, dirpath: Optional[str], rank: int, nprocs: int,
                 epoch: int = 0):
        self.dir = dirpath
        self.rank = int(rank)
        self.nprocs = int(nprocs)
        self.epoch = int(epoch)
        self.seq = 0
        self._mu = threading.Lock()
        self._f = None
        if dirpath:
            try:
                os.makedirs(dirpath, exist_ok=True)
                self._f = open(
                    os.path.join(dirpath,
                                 _log_name(self.epoch, self.rank)),
                    "a")
            except OSError as e:  # unusable dir: record-only mode
                sys.stderr.write(
                    f"bodo_tpu.lockstep: cannot open log in "
                    f"{dirpath!r} ({e}); peer checking disabled\n")
        self._peers: Dict[int, _PeerLog] = {}

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    def check(self, op: str, site: str) -> float:
        # the mesh-epoch field in the fingerprint makes a stale peer
        # (still dispatching under the old mesh) an immediate, named
        # mismatch instead of a confusing op-level divergence
        fingerprint = f"e{self.epoch}:{op}@{site}" if self.epoch \
            else f"{op}@{site}"
        with self._mu:
            self.seq += 1
            seq = self.seq
            if self._f is not None:
                # third field: wall-clock arrival stamp — per-seq skew
                # across ranks is reconstructed from these by doctor's
                # comm triage (the rank arriving LAST is the straggler)
                self._f.write(f"{seq}\t{fingerprint}\t{time.time():.6f}\n")
                self._f.flush()
        with _lock:
            _stats["collectives"] += 1
        if self.nprocs <= 1 or self._f is None:
            return 0.0
        t0 = time.monotonic()
        deadline = t0 + float(config.lockstep_timeout_s)
        for peer in range(self.nprocs):
            if peer == self.rank:
                continue
            plog = self._peers.get(peer)
            if plog is None:
                plog = self._peers[peer] = _PeerLog(os.path.join(
                    self.dir, _log_name(self.epoch, peer)))
            while True:
                got = plog.entry(seq)
                if got is not None:
                    if got != fingerprint:
                        with _lock:
                            _stats["mismatches"] += 1
                        err = LockstepError(
                            f"SPMD lockstep divergence at dispatch "
                            f"#{seq}: rank {self.rank} issued "
                            f"{fingerprint} but rank {peer} issued "
                            f"{got} — the ranks took different "
                            f"control-flow paths into a gang-scheduled "
                            f"op (this would have wedged the gang)",
                            seq=seq, rank=self.rank, peer=peer,
                            site=fingerprint, peer_site=got)
                        _flight_record(err)
                        raise err
                    break
                if time.monotonic() >= deadline:
                    with _lock:
                        _stats["timeouts"] += 1
                    err = LockstepError(
                        f"SPMD lockstep divergence at dispatch #{seq} "
                        f"({fingerprint}): rank {peer} did not reach "
                        f"dispatch #{seq} within "
                        f"{float(config.lockstep_timeout_s):.1f}s; its "
                        f"last dispatch was {plog.last()} — rank "
                        f"{peer} skipped the op or is wedged",
                        seq=seq, rank=self.rank, peer=peer,
                        site=fingerprint)
                    _flight_record(err)
                    raise err
                time.sleep(_POLL_S)
        wait = time.monotonic() - t0
        with _lock:
            _stats["wait_s"] += wait
            _stats["max_wait_s"] = max(_stats["max_wait_s"], wait)
        return wait
