"""Plan-graph distribution/shape validator (shardcheck layer 1).

Type-checks the logical plan DAG before execution: every operator's
inputs must resolve against its children's schemas, and the abstract
distribution of each subtree must satisfy the operator's contract.
Violations raise a structured :class:`PlanInvariantError` naming the
node, the rule, and the path from the plan root — BEFORE any kernel
traces or collectives dispatch, so a mis-typed plan fails in
milliseconds instead of wedging a gang-scheduled pod (the Pathways
divergent-collective failure class, arXiv:2203.12533).

Abstract distribution lattice (host-side, data-independent):

    REP   the subtree's result is always replicated on every process
    DIST  the result MAY be row-sharded (1D) over the mesh data axis —
          whether it actually is depends on runtime row counts
          (physical._maybe_shard's shard_min_rows policy)

The per-operator propagation rules live in two declarative tables:

  * OP_DIST — the abstract output distribution of each logical node as
    a function of its children's (what the *plan* may produce).
  * RUNTIME_RESULT_DIST — the distribution the relational-layer kernel
    actually RETURNS, for ops whose kernel result is pinned regardless
    of input distribution (gather-based paths). `check_kernel_result`
    cross-checks the real Table against this declaration at runtime, so
    a future rewrite of a kernel's distribution behavior (e.g. the
    planned shard-wise concat/append rebalance replacing the
    gather-to-host union path, relational.py concat_tables) cannot
    silently change typing: the rewrite must update the declaration —
    and therefore this validator — in the same change.

Entry points:

    validate_plan(node)          full-DAG validation; returns root dist
    dist_of(node)                abstract distribution of a subtree
    validate_rewrite(orig, new)  AQE re-plans must preserve schema+dist
    check_kernel_result(op, d)   runtime cross-check vs. declared dist

`physical.execute` calls `validate_plan` automatically when
`config.plan_validate` is on (default); `validate_plan` is also public
API for plan-building frontends.
"""

from __future__ import annotations

import sys
from typing import Dict

from bodo_tpu.plan.expr import expr_columns

# abstract distribution lattice
REP = "REP"
DIST = "DIST"  # may be row-sharded (1D) at runtime

_stats = {"plans": 0, "nodes": 0, "violations": 0, "kernel_checks": 0}


class PlanInvariantError(TypeError):
    """A plan (or a runtime kernel result) violates a distribution or
    shape invariant. Carries the offending node, the rule name, and the
    path from the plan root for structured handling."""

    def __init__(self, message: str, node=None, rule: str = "",
                 path: str = ""):
        self.node = node
        self.rule = rule
        self.path = path
        detail = message
        if rule:
            detail = f"[{rule}] {detail}"
        if node is not None:
            detail += f"\n  node: {node!r}"
        if path:
            detail += f"\n  path: {path}"
        super().__init__(detail)


def stats() -> dict:
    return dict(_stats)


def reset_stats() -> None:
    for k in _stats:
        _stats[k] = 0


# ---------------------------------------------------------------------------
# distribution propagation
# ---------------------------------------------------------------------------

def _any_dist(child_dists) -> str:
    return DIST if DIST in child_dists else REP


# node class name -> rule computing output dist from child dists.
# Sources are DIST (physical._maybe_shard may shard them); gather-based
# ops (Reduce/Limit) pin to REP; reshard-after-gather ops (Union,
# NonEquiJoin, Explode) are DIST even over all-REP children because
# _maybe_shard re-shards their kernel's replicated result when it grows
# past shard_min_rows.
OP_DIST = {
    "ReadParquet": lambda n, ds: DIST,
    "ReadCsv": lambda n, ds: DIST,
    "FromPandas": lambda n, ds: DIST,
    "Projection": lambda n, ds: ds[0],
    "Filter": lambda n, ds: ds[0],
    "Aggregate": lambda n, ds: ds[0],
    "Distinct": lambda n, ds: ds[0],
    "Window": lambda n, ds: ds[0],
    "RankWindow": lambda n, ds: ds[0],
    "AggWindow": lambda n, ds: ds[0],
    "Sort": lambda n, ds: ds[0],
    "Join": lambda n, ds: _any_dist(ds),
    "Reduce": lambda n, ds: REP,
    "Limit": lambda n, ds: REP,
    "Union": lambda n, ds: DIST,
    "NonEquiJoin": lambda n, ds: DIST,
    "Explode": lambda n, ds: DIST,
    # a ViewScan serves its view's materialization through
    # physical._maybe_shard, which may re-shard even a replicated
    # materialization once it grows past shard_min_rows — so like the
    # base source scans it is DIST regardless of the defining plan
    "ViewScan": lambda n, ds: DIST,
}

# what the relational-layer kernel RETURNS for ops whose result
# distribution is pinned by the kernel's implementation strategy (all
# currently gather-to-host paths). checked at runtime by
# check_kernel_result; see module docstring for why this is declared.
RUNTIME_RESULT_DIST = {
    "union": REP,        # relational.concat_tables gathers 1D inputs
    "head": REP,         # relational.head_table gathers
    "reduce": REP,       # relational.reduce_table returns host scalars
    "nonequi_join": REP,  # ops/nonequi runs on gathered inputs
    # a parquet scan materializes replicated on this host no matter
    # which decode route ran (host pyarrow OR io/device_decode's raw-
    # page programs) — the caller's _maybe_shard does the 1D reshard.
    # The device route builds Tables directly (no arrow_to_table), so
    # this is the contract keeping both routes interchangeable.
    "read_parquet": REP,
}


def check_kernel_result(op: str, distribution: str) -> None:
    """Cross-check a kernel's actual result distribution against its
    RUNTIME_RESULT_DIST declaration (no-op for undeclared ops)."""
    _stats["kernel_checks"] += 1
    declared = RUNTIME_RESULT_DIST.get(op)
    if declared is None:
        return
    # table-layer constants: "REP" / "1D"
    actual = REP if distribution == "REP" else DIST
    if declared == REP and actual != REP:
        _stats["violations"] += 1
        raise PlanInvariantError(
            f"kernel {op!r} returned a {distribution}-distributed table "
            f"but its declared result distribution is REP; if the kernel "
            f"was rewritten to keep results sharded (e.g. shard-wise "
            f"concat/append), update RUNTIME_RESULT_DIST and the "
            f"operator's OP_DIST rule together",
            rule="kernel-result-dist")


# ---------------------------------------------------------------------------
# per-node shape checks
# ---------------------------------------------------------------------------

def _err(node, path, rule, msg):
    _stats["violations"] += 1
    raise PlanInvariantError(msg, node=node, rule=rule, path=path)


def _check_refs(node, path, exprs_cols, child, what: str):
    """Expression/key column references must resolve in the child schema
    ("*" is the row-UDF wildcard: reads the whole row)."""
    missing = {c for c in exprs_cols if c != "*"} - set(child.schema)
    if missing:
        _err(node, path, "unknown-column",
             f"{type(node).__name__} {what} references columns "
             f"{sorted(missing)} not in child schema "
             f"{sorted(child.schema)}")


def _is_string(dtype) -> bool:
    return getattr(dtype, "kind", None) in ("string",) or \
        getattr(dtype, "name", "") == "string"


def _check_view_scan(node, path: str) -> None:
    """ViewScan leaf rules. Lazy: runtime/views.py is consulted only
    when already imported — a ViewScan can only be minted by
    views.scan_node, so the module is resident whenever a genuine plan
    carries one (a hand-built ViewScan with views never loaded
    validates permissively, matching the unknown-node default).

      unknown-view           the named view is not registered; execute
                             would fail deep inside materialization
      unsigned-view-sources  some leaf of the view's defining plan is
                             unsignable, so the result cache could not
                             sign — or ever invalidate — a consumer's
                             entry built over this scan
      view-schema-drift      the scan's snapshotted schema disagrees
                             with the live view (redefined since the
                             consumer plan was built): every downstream
                             column reference was checked against a
                             stale schema
      view-dist              the view's current materialization arrived
                             sharded (1D) where the defining plan's
                             root is abstractly REP — the fusion-input-
                             dist failure class at the view boundary
    """
    vw = sys.modules.get("bodo_tpu.runtime.views")
    if vw is None:
        return
    try:
        v = vw._get(node.name)
    except Exception:  # noqa: BLE001 — ViewError(ValueError)
        _err(node, path, "unknown-view",
             f"ViewScan references unregistered view {node.name!r}")
        return
    try:
        srcs = vw.base_sources(node.name)
    except Exception:  # noqa: BLE001
        srcs = None
    if srcs is None:
        _err(node, path, "unsigned-view-sources",
             f"view {node.name!r} has an unsignable leaf in its "
             f"defining plan: the result cache cannot sign or "
             f"invalidate entries built over this ViewScan")
    if list(node.schema) != list(v.schema):
        _err(node, path, "view-schema-drift",
             f"ViewScan snapshotted schema {sorted(node.schema)} "
             f"disagrees with live view {node.name!r} schema "
             f"{sorted(v.schema)} — the view was redefined after this "
             f"consumer plan was built")
    # materialization consistency: the defining plan's root caches its
    # last materialized Table in root._cached between refreshes
    cached = getattr(v.root, "_cached", None)
    if cached is not None and \
            getattr(cached, "distribution", None) == "1D" and \
            dist_of(v.root) == REP:
        _err(node, path, "view-dist",
             f"view {node.name!r} materialization is sharded (1D) but "
             f"its defining plan's root is abstractly REP — the "
             f"materializing kernel and the lattice disagree")


def _check_node(node, path: str) -> None:
    name = type(node).__name__
    if name in ("ReadParquet", "ReadCsv", "FromPandas"):
        if node.children:
            _err(node, path, "arity", f"{name} must be a leaf")
        return
    if name == "ViewScan":
        if node.children:
            _err(node, path, "arity", "ViewScan must be a leaf")
        _check_view_scan(node, path)
        return
    kids = node.children
    if name == "Projection":
        for n, e in node.exprs:
            _check_refs(node, path, expr_columns(e), kids[0],
                        f"expr {n!r}")
    elif name == "Filter":
        _check_refs(node, path, expr_columns(node.predicate), kids[0],
                    "predicate")
        if set(node.schema) != set(kids[0].schema):
            _err(node, path, "schema-drift",
                 "Filter must preserve its child's schema")
    elif name == "Aggregate":
        _check_refs(node, path, set(node.keys), kids[0], "keys")
        _check_refs(node, path, {c for c, _, _ in node.aggs}, kids[0],
                    "agg inputs")
        if not node.keys:
            _err(node, path, "empty-keys",
                 "Aggregate with no keys must be a Reduce")
    elif name == "Reduce":
        _check_refs(node, path, {c for c, _, _ in node.aggs}, kids[0],
                    "agg inputs")
    elif name == "Distinct":
        _check_refs(node, path, set(node.subset), kids[0], "subset")
    elif name == "Sort":
        _check_refs(node, path, set(node.by), kids[0], "sort keys")
        if len(node.by) != len(node.ascending):
            _err(node, path, "sort-spec",
                 f"{len(node.by)} sort keys but "
                 f"{len(node.ascending)} ascending flags")
    elif name == "Limit":
        if not isinstance(node.n, int) or node.n < 0:
            _err(node, path, "limit-n",
                 f"Limit n must be a non-negative int, got {node.n!r}")
    elif name in ("Window", "RankWindow", "AggWindow"):
        if name != "Window":
            _check_refs(node, path, set(node.partition_by), kids[0],
                        "partition_by")
            _check_refs(node, path, set(node.order_by), kids[0],
                        "order_by")
        cols = {s[0] for s in node.specs} if name == "Window" else \
            {s[1] for s in node.specs} if name == "AggWindow" else set()
        _check_refs(node, path, {c for c in cols if isinstance(c, str)},
                    kids[0], "spec inputs")
    elif name == "Union":
        first = list(kids[0].schema)
        for c in kids[1:]:
            if list(c.schema) != first:
                _err(node, path, "union-schema",
                     f"Union children disagree on schema: {first} vs "
                     f"{list(c.schema)}")
    elif name == "Join":
        if node.how != "cross":
            if not node.left_on or \
                    len(node.left_on) != len(node.right_on):
                _err(node, path, "join-keys",
                     f"Join needs matching non-empty key lists, got "
                     f"left_on={node.left_on} right_on={node.right_on}")
            _check_refs(node, path, set(node.left_on), kids[0],
                        "left_on")
            _check_refs(node, path, set(node.right_on), kids[1],
                        "right_on")
            for lk, rk in zip(node.left_on, node.right_on):
                lt, rt = kids[0].schema[lk], kids[1].schema[rk]
                # conservative: only a string/non-string mismatch is
                # certainly wrong (numerics promote, dates compare)
                if _is_string(lt) != _is_string(rt):
                    _err(node, path, "join-key-dtype",
                         f"join key dtype mismatch: {lk}:{lt.name} vs "
                         f"{rk}:{rt.name}")
    elif name == "NonEquiJoin":
        overlap = set(kids[0].schema) & set(kids[1].schema)
        if overlap:
            _err(node, path, "nonequi-names",
                 f"NonEquiJoin children share column names {overlap}")
        combined = set(kids[0].schema) | set(kids[1].schema)
        missing = {c for c in expr_columns(node.pred) if c != "*"} \
            - combined
        if missing:
            _err(node, path, "unknown-column",
                 f"NonEquiJoin predicate references {sorted(missing)} "
                 f"outside the combined schema")
    elif name == "Explode":
        if node.column not in kids[0].schema:
            _err(node, path, "unknown-column",
                 f"Explode column {node.column!r} not in child schema")
        elif getattr(kids[0].schema[node.column], "kind", "") != "list":
            _err(node, path, "explode-dtype",
                 f"Explode input {node.column!r} is not a list column")


# ---------------------------------------------------------------------------
# walk
# ---------------------------------------------------------------------------

def _validate(node, path: str, onpath: set,
              memo: Dict[int, str]) -> str:
    got = memo.get(id(node))
    if got is not None:
        return got
    if id(node) in onpath:
        _err(node, path, "cycle", "plan DAG contains a cycle")
    onpath.add(id(node))
    _stats["nodes"] += 1
    name = type(node).__name__
    sub = f"{path}/{name}" if path else name
    kid_dists = [_validate(c, sub, onpath, memo)
                 for c in node.children]
    _check_node(node, sub)
    rule = OP_DIST.get(name)
    # unknown/future node types: validated children, permissive DIST
    d = rule(node, kid_dists) if rule is not None else DIST
    onpath.discard(id(node))
    memo[id(node)] = d
    return d


def validate_plan(node) -> str:
    """Validate a whole logical plan; returns the root's abstract
    distribution (REP/DIST). Raises PlanInvariantError on the first
    violation. Cheap: one DFS, no execution, results memoized per call
    (shared sub-DAGs validate once)."""
    _stats["plans"] += 1
    return _validate(node, "", set(), {})


def dist_of(node) -> str:
    """Abstract distribution of a subtree without full validation."""
    name = type(node).__name__
    rule = OP_DIST.get(name)
    if rule is None:
        return DIST
    return rule(node, [dist_of(c) for c in node.children])


def check_fusion_boundary(input_node, input_dist: str,
                          force_rep: bool = False) -> None:
    """Shardcheck at a whole-stage-fusion group edge: the runtime
    distribution of the group's input table must be consistent with the
    lattice's abstract prediction for the input subtree. A fused program
    is compiled with explicit shardings derived from that prediction, so
    an abstractly-REP input arriving sharded would dispatch a
    replicated-spec program over 1D data — exactly the silent-wrong-
    answer class the lattice exists to catch. Called by
    plan/fusion.execute_group right before group dispatch (skipped when
    a degraded re-run forced the input replicated: gathering is then the
    POINT, not a violation)."""
    if force_rep:
        return
    abstract = dist_of(input_node)
    runtime = "DIST" if input_dist == "1D" else "REP"
    if abstract == REP and runtime == DIST:
        _stats["violations"] += 1
        raise PlanInvariantError(
            f"fusion group input {type(input_node).__name__} is "
            f"abstractly REP but arrived sharded (1D) at dispatch — "
            f"the fused program's shardings would be wrong",
            node=input_node, rule="fusion-input-dist")


def validate_rewrite(orig, repl) -> None:
    """AQE re-plans (plan/adaptive.py join re-ordering) must preserve
    the original subtree's schema (names+dtypes, in order) and abstract
    distribution — a rewrite that widens REP to DIST (or reorders
    columns) would silently change downstream typing."""
    validate_plan(repl)
    if list(orig.schema) != list(repl.schema):
        _stats["violations"] += 1
        raise PlanInvariantError(
            f"AQE rewrite changed the output schema: "
            f"{list(orig.schema)} -> {list(repl.schema)}",
            node=repl, rule="rewrite-schema")
    for n in orig.schema:
        if orig.schema[n] is not repl.schema[n] and \
                orig.schema[n].name != repl.schema[n].name:
            _stats["violations"] += 1
            raise PlanInvariantError(
                f"AQE rewrite changed dtype of {n!r}: "
                f"{orig.schema[n].name} -> {repl.schema[n].name}",
                node=repl, rule="rewrite-dtype")
    if dist_of(orig) == REP and dist_of(repl) != REP:
        _stats["violations"] += 1
        raise PlanInvariantError(
            "AQE rewrite widened a replicated subtree to a possibly "
            "sharded one", node=repl, rule="rewrite-dist")
