"""shardcheck codebase lint (shardcheck layer 2) — stdlib-`ast` rules
for SPMD safety over the bodo_tpu package itself.

Rules:

  rank-divergent-collective
      A collective call lexically inside control flow whose condition
      depends on the process/shard identity (rank, process_index,
      BODO_TPU_PROC_ID, axis_index). In gang-scheduled SPMD one rank
      skipping a collective hangs every other rank (Pathways,
      arXiv:2203.12533) — divergent ranks must never reach a
      collective.

  trace-time-side-effect
      A host side effect (I/O, environ, time, random, fault injection)
      inside a function that is traced by jax (contains lax collectives
      or is passed to smap/shard_map). Traced bodies run ONCE at trace
      time and never again from the compiled-kernel cache, so the side
      effect silently stops firing — the PR-2 trace-time-vs-
      dispatch-time distinction as a checked rule.

  retry-non-idempotent
      A non-idempotent operation (write/send/append) inside a callable
      passed to `resilience.retry_call`. A transient failure AFTER the
      effect lands re-runs the effect (duplicate rows / double
      writes) — the ParquetWriter class of bug from the PR-2 review.

  checkpoint-non-idempotent
      A non-idempotent operation (write/send/append) between a
      checkpoint store's two-phase `.register(...)` and its
      `.commit(tok)`. The window is exactly the span a crash discards:
      the snapshot is not yet visible to recovery, so an effect landed
      there replays when the elastic suffix resumes from the PREVIOUS
      checkpoint (duplicate write) — keep the register->commit window
      effect-free.

  unlocked-shared-state
      A write to module-level mutable state outside any `with <lock>:`
      block, in modules that define threading locks (i.e. modules whose
      state is demonstrably shared across threads — the io_pool/pool
      worker-thread model). Modules with no locks are single-threaded
      by design and out of scope.

  fusion-host-call
      A host-sync call (`jax.device_get`, `.to_pandas()`,
      `device_put`, `.block_until_ready()`) inside a function marked
      `@fusion_stage` (plan/fusion.py). Fusion stages run INSIDE one
      compiled whole-stage program; a host round-trip there either
      fails to trace or silently splits the fused program at an
      unsharded boundary — the exact materialization fusion exists to
      eliminate.

  rank-divergent-rng-seed
      An RNG seeded from process/shard identity (np.random.seed /
      default_rng / PRNGKey over rank, process_index,
      BODO_TPU_PROC_ID, ...). Rank-variant seeds silently diverge
      REPLICATED state: every rank holds "the same" table, fills nulls
      or samples with "the same" RNG, and ends up with different
      bytes — the gang then disagrees at the next content-keyed
      collective or cache lookup. Shard-local sampling must derive
      from a rank-INVARIANT seed plus an explicit fold
      (jax.random.fold_in), never from seeding with the rank itself.

  divergent-host-sync
      A host sync (`jax.device_get` / `.block_until_ready()`) under
      control flow conditioned on process/shard identity. Fetching a
      SHARDED array is a cross-host transfer on multi-host backends —
      ranks that skipped the branch never enter it, so the fetching
      rank wedges exactly like a skipped collective (the
      rank-divergent-collective rule's host-side twin).

  stream-sync-unannotated
      A host sync (`jax.device_get` / `.block_until_ready()`) inside a
      streaming accumulator module (plan/streaming*.py), a fused-join
      dispatch body (plan/fusion_join.py), or a view step/maintenance
      body (runtime/views.py functions whose name carries step/
      maintenance/tick/refresh/materialize) without a
      `# dispatch-boundary` comment on the call or an adjacent line.
      Streaming steps — and the view-maintenance path that rides the
      same executors — are dispatch-free by design — syncs per stage
      must stay O(1)-O(log batches), so every deliberate sync site is
      annotated and counted in `stream_stats`; an unannotated sync is
      either an accidental pipeline stall (O(batches) regression) or
      an uncounted one the bench can't regress on.

Suppressions: `# shardcheck: ignore[rule]` (or bare
`# shardcheck: ignore` for all rules) on the finding's line or the
line directly above. Grandfathered findings live in
`analysis/baseline.json`, matched line-number-insensitively on
(rule, file, enclosing function, source text) so unrelated edits don't
resurrect them; `python -m bodo_tpu.analysis --write-baseline`
regenerates it, and `--prune-baseline` drops DEAD entries (ones no
current finding matches) without touching live ones.

Exit status (CLI): 0 when every finding is suppressed or baselined,
1 otherwise — `runtests.py lint` gates on this. A full-package run
also fails (exit 1) on dead baseline entries, so the baseline can only
shrink as findings are fixed.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Optional, Set

RULES = {
    "rank-divergent-collective":
        "collective dispatched under rank-dependent control flow",
    "trace-time-side-effect":
        "host side effect inside a jax-traced function body",
    "retry-non-idempotent":
        "non-idempotent operation inside the retry envelope",
    "checkpoint-non-idempotent":
        "side effect inside the checkpoint register->commit window",
    "unlocked-shared-state":
        "module-global state written without holding a lock",
    "fusion-host-call":
        "host sync inside a @fusion_stage-decorated traced body",
    "swallowed-collective":
        "collective inside a try whose handler swallows divergence",
    "unregistered-jit":
        "jit/pallas_call site bypassing the program registry",
    "rank-divergent-rng-seed":
        "RNG seeded from process/shard identity",
    "divergent-host-sync":
        "host sync of device arrays under rank-dependent control flow",
    "stream-sync-unannotated":
        "host sync in a streaming step body without a "
        "dispatch-boundary annotation",
}

# names that identify process/shard identity in a branch condition
_RANK_NAMES = {"rank", "process_index", "process_id", "proc_id",
               "current_rank", "axis_index"}
_RANK_ENV = {"BODO_TPU_PROC_ID"}

# axis-context collectives (lax + this package's wrappers) and the
# host-level dispatch helpers: calling any of these from one rank only
# wedges the gang
_COLLECTIVE_NAMES = {
    "psum", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
    "pshuffle", "psum_scatter",
    "dist_sum", "dist_max", "dist_min", "dist_exscan_sum",
    "all_gather_rows", "all_to_all_rows", "ring_shift", "bcast_from",
    "shuffle_rows", "shuffle_by_key",
}
# lax-only subset used to classify a function as jax-traced
_LAX_COLLECTIVES = {"psum", "pmax", "pmin", "all_gather", "all_to_all",
                    "ppermute", "pshuffle", "psum_scatter",
                    "axis_index"}

_SIDE_EFFECT_NAMES = {"open", "print", "maybe_inject", "_inject",
                      "input"}
_SIDE_EFFECT_MODULES = {"os", "time", "random"}
# pure/trace-safe exceptions within those modules
_SIDE_EFFECT_OK = {"time.monotonic", "time.perf_counter", "time.time",
                   "os.path", "random.Random"}

_NONIDEMPOTENT = {"write", "writelines", "write_table", "send",
                  "sendall", "appendleft", "append_row"}

# receivers that look like a two-phase checkpoint store: their
# .register(...) opens an uncommitted-snapshot window that .commit(tok)
# closes (runtime/elastic.py CheckpointStore is the canonical one)
_CKPT_RECV_RE = re.compile(r"ckpt|checkpoint|store", re.IGNORECASE)

# host-sync calls illegal inside a @fusion_stage body (whole-stage
# fusion: the body runs inside ONE compiled program)
_HOST_SYNC_NAMES = {"device_get", "to_pandas", "device_put",
                    "block_until_ready"}

# host syncs that are cross-host transfers for sharded arrays — under
# rank-divergent control flow they wedge like a skipped collective
_DIVERGENT_SYNC_NAMES = {"device_get", "block_until_ready"}

# streaming accumulator modules: every host sync in a step body must be
# a deliberate, annotated dispatch boundary (plan/streaming.py's
# host-sync accounting contract). plan/fusion_join.py rides the same
# contract whole-module (its group dispatch is the one budgeted sync);
# runtime/views.py only in step/maintenance bodies (the serving-path
# refresh loop), matched by enclosing-function name.
_STREAMING_FILE_RE = re.compile(r"(^|[/\\])plan[/\\]streaming[^/\\]*\.py$")
_STREAM_WHOLE_FILE_RE = re.compile(r"(^|[/\\])plan[/\\]fusion_join\.py$")
_STREAM_SCOPED_FILE_RE = re.compile(r"(^|[/\\])runtime[/\\]views\.py$")
_STREAM_SCOPED_FUNC_RE = re.compile(
    r"step|maintenance|tick|refresh|materialize")
_DISPATCH_BOUNDARY_RE = re.compile(r"#\s*dispatch-boundary")

# RNG seeding entry points (numpy + jax.random)
_RNG_SEED_NAMES = {"seed", "default_rng", "PRNGKey", "RandomState"}

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_LOCKISH_RE = re.compile(r"(lock|_mu$|mutex|cv$|cond)", re.IGNORECASE)
_MUTATORS = {"append", "extend", "add", "update", "pop", "popitem",
             "clear", "remove", "discard", "insert", "setdefault",
             "appendleft"}

_SUPPRESS_RE = re.compile(
    r"#\s*shardcheck:\s*ignore(?:\[([\w\-, ]+)\])?")


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative
    line: int
    col: int
    func: str          # enclosing function qualname ("" = module)
    text: str          # source line, stripped
    message: str

    def key(self):
        """Line-number-insensitive identity for baseline matching."""
        return (self.rule, self.path, self.func, self.text)

    def render(self) -> str:
        where = f" (in {self.func})" if self.func else ""
        return (f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
                f"{self.message}{where}\n    {self.text}")


_stats = {"runs": 0, "files": 0, "findings": 0, "suppressed": 0,
          "baselined": 0}


def stats() -> dict:
    return dict(_stats)


def _terminal(func) -> str:
    """Rightmost name of a call target (foo / mod.foo / a.b.foo)."""
    if isinstance(func, ast.Attribute):
        return func.attr
    return func.id if isinstance(func, ast.Name) else ""


def _root(node) -> str:
    """Leftmost name of an attribute chain (os.environ.get -> os)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _dotted(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _test_is_rank_divergent(test: ast.AST) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id in _RANK_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RANK_NAMES:
            return True
        if isinstance(n, ast.Call) and _terminal(n.func) in _RANK_NAMES:
            return True
        if isinstance(n, ast.Constant) and n.value in _RANK_ENV:
            return True
    return False


class _ModuleInfo(ast.NodeVisitor):
    """Pre-pass: module-level names, locks, traced functions, and
    retry_call targets."""

    def __init__(self):
        self.globals: Set[str] = set()        # module-level bindings
        self.mutables: Set[str] = set()       # dict/list/set/deque/...
        self.locks: Set[str] = set()          # Lock()/RLock()/...
        self.smap_fn_names: Set[str] = set()  # passed to smap/shard_map

    def visit_Module(self, node: ast.Module):
        for stmt in node.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = [t for t in stmt.targets
                           if isinstance(t, ast.Name)]
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            for t in targets:
                self.globals.add(t.id)
                if isinstance(value, ast.Call):
                    name = _terminal(value.func)
                    if name in _LOCK_FACTORIES:
                        self.locks.add(t.id)
                    elif name in ("dict", "list", "set", "deque",
                                  "defaultdict", "OrderedDict",
                                  "Counter"):
                        self.mutables.add(t.id)
                elif isinstance(value, (ast.Dict, ast.List, ast.Set,
                                        ast.DictComp, ast.ListComp,
                                        ast.SetComp)):
                    self.mutables.add(t.id)
        # whole-tree scan for smap/shard_map(fn, ...) first args
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and \
                    _terminal(n.func) in ("smap", "shard_map") and \
                    n.args and isinstance(n.args[0], ast.Name):
                self.smap_fn_names.add(n.args[0].id)


# a store like `cache[key] = fn` / `_programs[sig] = fn` / `_jit_cache
# [key] = fn` marks the enclosing function as registering its compiled
# programs with a kernel cache (which reports to the program registry)
_CACHE_NAME_HINTS = ("cache", "program")


def _stores_into_kernel_cache(fn: ast.AST) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Subscript):
                    name = (_terminal(t.value)
                            if isinstance(t.value, ast.Attribute)
                            else getattr(t.value, "id", ""))
                    low = name.lower()
                    if any(h in low for h in _CACHE_NAME_HINTS):
                        return True
    return False


def _has_registering_decorator(fn: ast.AST) -> bool:
    """@cached_builder("sub") / @bounded_jit memoize the function's
    compiled programs in a registered KernelCache."""
    for d in getattr(fn, "decorator_list", []):
        t = _terminal(d.func) if isinstance(d, ast.Call) else _terminal(d)
        if t in ("cached_builder", "bounded_jit"):
            return True
    return False


def _is_jit_decorator(d: ast.AST) -> bool:
    """@jax.jit, or @partial(jax.jit, ...) / @functools.partial(...)."""
    if _dotted(d) == "jax.jit":
        return True
    if isinstance(d, ast.Call) and _terminal(d.func) == "jit" \
            and _root(d.func) == "jax":
        return True
    if isinstance(d, ast.Call) and _terminal(d.func) == "partial":
        for a in d.args:
            if _dotted(a) == "jax.jit":
                return True
    return False


def _contains_lax_collective(fn: ast.AST) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and \
                _terminal(n.func) in _LAX_COLLECTIVES:
            return True
    return False


def _calls_in_order(fn: ast.AST) -> List[ast.Call]:
    """Call nodes lexically inside ``fn``'s own body — nested
    function/lambda bodies excluded (they execute at their OWN call
    time, not inside this function's checkpoint window) — in source
    order."""
    out: List[ast.Call] = []

    def rec(n: ast.AST) -> None:
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(c, ast.Call):
                out.append(c)
            rec(c)

    rec(fn)
    out.sort(key=lambda c: (getattr(c, "lineno", 0),
                            getattr(c, "col_offset", 0)))
    return out


class _Checker(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, src_lines: List[str],
                 info: _ModuleInfo,
                 dispatch_lines: Optional[Set[int]] = None):
        self.rel = rel
        self.lines = src_lines
        self.info = info
        self.dispatch_lines = dispatch_lines or set()
        rel_posix = rel.replace(os.sep, "/")
        self._stream_mod = bool(
            _STREAMING_FILE_RE.search(rel_posix)
            or _STREAM_WHOLE_FILE_RE.search(rel_posix))
        self._stream_scoped = bool(
            _STREAM_SCOPED_FILE_RE.search(rel_posix))
        self.findings: List[Finding] = []
        self._func: List[str] = []       # qualname stack
        self._div_depth = 0              # rank-divergent control flow
        self._locks_held = 0             # `with <lock>:` nesting
        self._traced_depth = 0           # inside a jax-traced function
        self._fusion_depth = 0           # inside a @fusion_stage body
        self._reg_depth = 0              # fn stores into a kernel cache
        self._local_defs: List[Dict[str, ast.AST]] = [{}]

    # -- helpers ----------------------------------------------------------

    def _add(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        text = self.lines[line - 1].strip() if \
            0 < line <= len(self.lines) else ""
        self.findings.append(Finding(
            rule=rule, path=self.rel, line=line,
            col=getattr(node, "col_offset", 0),
            func=".".join(self._func), text=text, message=message))

    def _qual(self, name: str) -> str:
        return ".".join(self._func + [name])

    # -- scopes -----------------------------------------------------------

    def _visit_func(self, node):
        self._local_defs[-1][node.name] = node
        traced = (node.name in self.info.smap_fn_names or
                  _contains_lax_collective(node))
        fused = any(_terminal(d) == "fusion_stage"
                    for d in node.decorator_list)
        registers = (_stores_into_kernel_cache(node) or
                     _has_registering_decorator(node))
        for d in node.decorator_list:
            # a @jax.jit on a local function whose enclosing scope
            # stores it into a kernel cache IS registered
            if not self._reg_depth and not registers \
                    and _is_jit_decorator(d):
                self._add(
                    "unregistered-jit", d,
                    "module-lifetime @jit decorator: pins one "
                    "executable per signature forever, invisible to "
                    "the program registry and its compile budget — "
                    "route through bounded_jit or a registered "
                    "KernelCache")
        self._func.append(node.name)
        self._check_checkpoint_windows(node)
        self._local_defs.append({})
        if traced:
            self._traced_depth += 1
        if fused:
            self._fusion_depth += 1
        if registers:
            self._reg_depth += 1
        # a lock held at the call site does not cover the function body
        saved_locks, self._locks_held = self._locks_held, 0
        self.generic_visit(node)
        self._locks_held = saved_locks
        if registers:
            self._reg_depth -= 1
        if fused:
            self._fusion_depth -= 1
        if traced:
            self._traced_depth -= 1
        self._local_defs.pop()
        self._func.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- rank-divergent control flow --------------------------------------

    def _visit_branch(self, node):
        divergent = _test_is_rank_divergent(node.test)
        if divergent:
            self._div_depth += 1
        self.generic_visit(node)
        if divergent:
            self._div_depth -= 1

    visit_If = _visit_branch
    visit_While = _visit_branch
    visit_IfExp = _visit_branch

    # -- with <lock>: -----------------------------------------------------

    def visit_With(self, node: ast.With):
        lockish = 0
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                expr = expr.func  # reserve(...), lock() factories
            name = _dotted(expr)
            leaf = name.rsplit(".", 1)[-1] if name else ""
            if leaf in self.info.locks or _LOCKISH_RE.search(leaf or ""):
                lockish += 1
        self._locks_held += lockish
        self.generic_visit(node)
        self._locks_held -= lockish

    # -- try/except around collectives ------------------------------------

    # exception names wide enough to catch a lockstep divergence (or
    # any gang-consistency error) — swallowing one desynchronizes the
    # swallowing rank from peers still inside (or dead at) the op
    _BROAD_EXC = {"Exception", "BaseException", "LockstepError"}

    def _handler_swallows(self, h: ast.ExceptHandler) -> bool:
        names: Set[str] = set()
        if h.type is None:
            names.add("BaseException")  # bare except
        else:
            types = h.type.elts if isinstance(h.type, ast.Tuple) \
                else [h.type]
            for tnode in types:
                names.add(_terminal(tnode) if
                          isinstance(tnode, ast.Call) else
                          _dotted(tnode).rsplit(".", 1)[-1])
        if not names & self._BROAD_EXC:
            return False
        # a handler that re-raises (or exits the process) propagates
        # the divergence instead of swallowing it
        for n in ast.walk(h):
            if isinstance(n, ast.Raise):
                return False
            if isinstance(n, ast.Call) and \
                    _terminal(n.func) in ("_exit", "exit", "abort"):
                return False
        return True

    def visit_Try(self, node: ast.Try):
        swallowing = [h for h in node.handlers
                      if self._handler_swallows(h)]
        if swallowing:
            for n in ast.walk(ast.Module(body=node.body,
                                         type_ignores=[])):
                if isinstance(n, ast.Call) and \
                        _terminal(n.func) in _COLLECTIVE_NAMES:
                    t = _terminal(n.func)
                    self._add(
                        "swallowed-collective", n,
                        f"collective {t!r} inside a try whose handler "
                        f"catches broadly without re-raising: a "
                        f"divergence error (LockstepError) raised here "
                        f"is swallowed on THIS rank while peers wedge "
                        f"in (or die at) the op — catch narrowly or "
                        f"re-raise")
        self.generic_visit(node)

    # -- calls ------------------------------------------------------------

    def visit_Call(self, node: ast.Call):
        t = _terminal(node.func)
        if self._div_depth and t in _COLLECTIVE_NAMES:
            self._add(
                "rank-divergent-collective", node,
                f"collective {t!r} dispatched under rank-dependent "
                f"control flow: ranks taking the other branch never "
                f"enter the collective and the gang hangs")
        if self._div_depth and t in _DIVERGENT_SYNC_NAMES:
            self._add(
                "divergent-host-sync", node,
                f"{t!r} under rank-dependent control flow: fetching a "
                f"sharded array is a cross-host transfer — ranks that "
                f"took the other branch never participate, wedging "
                f"this rank like a skipped collective")
        in_stream_body = self._stream_mod or (
            self._stream_scoped and any(
                _STREAM_SCOPED_FUNC_RE.search(fn) for fn in self._func))
        if in_stream_body and self._func and \
                t in _DIVERGENT_SYNC_NAMES:
            lo = getattr(node, "lineno", 1) - 1
            hi = getattr(node, "end_lineno", lo + 1) + 1
            if not any(ln in self.dispatch_lines
                       for ln in range(lo, hi + 1)):
                self._add(
                    "stream-sync-unannotated", node,
                    f"{t!r} in a streaming step body without a "
                    f"`# dispatch-boundary` annotation: streaming "
                    f"stages budget O(1)-O(log batches) syncs — mark "
                    f"the site deliberate (and _note_sync() it) or "
                    f"hoist the fetch out of the per-batch path")
        if t in _RNG_SEED_NAMES and (node.args or node.keywords) and \
                any(_test_is_rank_divergent(a)
                    for a in list(node.args) +
                    [k.value for k in node.keywords]):
            self._add(
                "rank-divergent-rng-seed", node,
                f"{t!r} seeded from process/shard identity: replicated "
                f"state sampled from it silently diverges across "
                f"ranks — derive shard-local streams from a "
                f"rank-invariant seed via jax.random.fold_in instead")
        if self._traced_depth:
            dotted = _dotted(node.func)
            if (t in _SIDE_EFFECT_NAMES or
                (_root(node.func) in _SIDE_EFFECT_MODULES and
                 not any(dotted.startswith(ok)
                         for ok in _SIDE_EFFECT_OK))):
                self._add(
                    "trace-time-side-effect", node,
                    f"{dotted or t!r} inside a jax-traced body fires "
                    f"at TRACE time only (compiled kernels are cached "
                    f"and replay without it)")
        if self._fusion_depth and t in _HOST_SYNC_NAMES:
            self._add(
                "fusion-host-call", node,
                f"{t!r} inside a @fusion_stage body: fusion stages "
                f"trace into ONE compiled program — a host sync here "
                f"splits the fused pipeline (or fails to trace)")
        if not self._reg_depth and \
                ((t == "jit" and _root(node.func) == "jax")
                 or t == "pallas_call"):
            self._add(
                "unregistered-jit", node,
                f"direct {_dotted(node.func) or t!r} call outside a "
                f"registering cache: the executable bypasses the "
                f"program registry (no retrace attribution, no "
                f"compile budget, unbounded pinning) — store it in a "
                f"subsystem-tagged KernelCache or use bounded_jit")
        if t == "retry_call" and node.args:
            self._check_retry_target(node)
        # dict.setdefault-style mutations via call are handled in the
        # mutation visitors below; nothing else to do here
        self.generic_visit(node)

    def _check_retry_target(self, node: ast.Call) -> None:
        target = node.args[0]
        body: Optional[ast.AST] = None
        if isinstance(target, ast.Lambda):
            body = target
        elif isinstance(target, ast.Name):
            for scope in reversed(self._local_defs):
                if target.id in scope:
                    body = scope[target.id]
                    break
        if body is None:
            return
        for n in ast.walk(body):
            if isinstance(n, ast.Call):
                meth = _terminal(n.func)
                if meth in _NONIDEMPOTENT and \
                        isinstance(n.func, ast.Attribute):
                    self._add(
                        "retry-non-idempotent", node,
                        f"retry envelope wraps non-idempotent "
                        f"`.{meth}(...)`: a transient failure after "
                        f"the effect lands replays it (duplicate "
                        f"write)")
                    return

    def _check_checkpoint_windows(self, fn) -> None:
        """Linear source-order scan of this function's calls: a
        ``<ckpt-store>.register(...)`` opens an uncommitted-snapshot
        window that the matching ``<ckpt-store>.commit(...)`` closes;
        any non-idempotent effect inside the window replays on elastic
        resume (the snapshot it rode with was never committed)."""
        open_regs: Dict[str, ast.Call] = {}
        for c in _calls_in_order(fn):
            if not isinstance(c.func, ast.Attribute):
                continue
            t = c.func.attr
            recv = _dotted(c.func.value)
            if t == "register" and recv and _CKPT_RECV_RE.search(recv):
                open_regs[recv] = c
                continue
            if t == "commit" and recv in open_regs:
                del open_regs[recv]
                continue
            if open_regs and t in _NONIDEMPOTENT:
                stores = ", ".join(sorted(open_regs))
                self._add(
                    "checkpoint-non-idempotent", c,
                    f"non-idempotent `.{t}(...)` between "
                    f"{stores!r}.register() and its commit: a crash "
                    f"here discards the registered snapshot, so the "
                    f"resumed suffix replays this effect (duplicate "
                    f"write) — move it after commit or make it "
                    f"idempotent")

    # -- shared-state mutation --------------------------------------------

    def _mutation(self, node, name: str, how: str) -> None:
        if not self.info.locks:           # module has no threads/locks
            return
        if not self._func:                # module top level: init time
            return
        if self._locks_held:
            return
        self._add(
            "unlocked-shared-state", node,
            f"module-global {name!r} {how} without holding any of "
            f"this module's locks "
            f"({', '.join(sorted(self.info.locks))})")

    def visit_Global(self, node: ast.Global):
        # remember rebindable globals for this function scope
        self._global_decls = getattr(self, "_global_decls", {})
        self._global_decls.setdefault(".".join(self._func),
                                      set()).update(node.names)
        self.generic_visit(node)

    def _rebinds_global(self, name: str) -> bool:
        decls = getattr(self, "_global_decls", {})
        return name in decls.get(".".join(self._func), set())

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._check_store(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_store(node.target, node)
        self.generic_visit(node)

    def _check_store(self, target, node) -> None:
        if isinstance(target, ast.Name):
            if target.id in self.info.globals and \
                    self._rebinds_global(target.id):
                self._mutation(node, target.id, "rebound")
        elif isinstance(target, ast.Subscript):
            base = target.value
            if isinstance(base, ast.Name) and \
                    base.id in self.info.mutables:
                self._mutation(node, base.id, "item-assigned")

    def visit_Expr(self, node: ast.Expr):
        # `_cache.update(...)`-style mutator method calls
        v = node.value
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute):
            base = v.func.value
            if isinstance(base, ast.Name) and \
                    base.id in self.info.mutables and \
                    v.func.attr in _MUTATORS:
                self._mutation(node, base.id,
                               f"mutated via .{v.func.attr}()")
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# suppressions / baseline
# ---------------------------------------------------------------------------

def _dispatch_boundary_lines(source: str) -> Set[int]:
    """Lines carrying a `# dispatch-boundary` comment (tokenize-based,
    so the marker inside a string/docstring does not count)."""
    out: Set[int] = set()
    try:
        tokens = tokenize.generate_tokens(
            iter(source.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type == tokenize.COMMENT and \
                    _DISPATCH_BOUNDARY_RE.search(tok.string):
                out.add(tok.start[0])
    except tokenize.TokenError:
        pass
    return out


def _suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> suppressed rule set (None = all rules). A comment
    suppresses its own line and the line below it."""
    out: Dict[int, Optional[Set[str]]] = {}
    try:
        tokens = tokenize.generate_tokens(
            iter(source.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = None
            if m.group(1):
                rules = {r.strip() for r in m.group(1).split(",")}
            for line in (tok.start[0], tok.start[0] + 1):
                prev = out.get(line, set())
                out[line] = None if rules is None or prev is None \
                    else prev | rules
    except tokenize.TokenError:
        pass
    return out


def _is_suppressed(f: Finding,
                   supp: Dict[int, Optional[Set[str]]]) -> bool:
    if f.line not in supp:
        return False
    rules = supp[f.line]
    return rules is None or f.rule in rules


def load_baseline(path: str) -> List[tuple]:
    try:
        with open(path) as fh:
            raw = json.load(fh)
    except (OSError, ValueError):
        return []
    return [(e["rule"], e["file"], e.get("func", ""), e["text"])
            for e in raw if isinstance(e, dict)]


def write_baseline(path: str, findings: List[Finding]) -> None:
    _write_baseline_keys(path, [f.key() for f in findings])


def _write_baseline_keys(path: str, keys: List[tuple]) -> None:
    entries = [{"rule": rule, "file": file, "func": func, "text": text}
               for rule, file, func, text in keys]
    with open(path, "w") as fh:
        json.dump(entries, fh, indent=1, sort_keys=True)
        fh.write("\n")


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "baseline.json")


def lint_file(path: str, root: Optional[str] = None) -> List[Finding]:
    """Lint one file; findings suppressed inline are dropped (counted
    in stats)."""
    root = root or os.path.dirname(path)
    rel = os.path.relpath(path, root)
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rule="parse-error", path=rel,
                        line=e.lineno or 1, col=0, func="",
                        text="", message=str(e))]
    info = _ModuleInfo()
    info.visit_Module(tree)
    checker = _Checker(path, rel, source.splitlines(), info,
                       dispatch_lines=_dispatch_boundary_lines(source))
    checker.visit(tree)
    supp = _suppressions(source)
    kept = []
    for f in checker.findings:
        if _is_suppressed(f, supp):
            _stats["suppressed"] += 1
        else:
            kept.append(f)
    _stats["files"] += 1
    return kept


def lint_paths(paths, root: Optional[str] = None) -> List[Finding]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                files += [os.path.join(dirpath, fn)
                          for fn in filenames if fn.endswith(".py")]
        elif p.endswith(".py"):
            files.append(p)
    out: List[Finding] = []
    for f in sorted(files):
        out += lint_file(f, root=root)
    return out


def lint_package() -> List[Finding]:
    """Lint the installed bodo_tpu package (what the CI gate runs)."""
    return lint_paths([_PKG_DIR], root=os.path.dirname(_PKG_DIR))


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m bodo_tpu.analysis",
        description="shardcheck: SPMD safety lint over bodo_tpu/")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON of grandfathered findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline entries no current finding "
                         "matches (keeps live ones untouched)")
    args = ap.parse_args(argv)
    _stats["runs"] += 1
    if args.paths:
        findings = lint_paths(args.paths, root=os.getcwd())
    else:
        findings = lint_package()
    _stats["findings"] += len(findings)
    if args.write_baseline:
        write_baseline(args.baseline, findings)
        print(f"shardcheck: wrote {len(findings)} baseline entries to "
              f"{args.baseline}")
        return 0
    live_keys = {f.key() for f in findings}
    if args.prune_baseline:
        if args.paths:
            # a partial scan would read unscanned files' entries as
            # falsely dead and silently delete them
            print("shardcheck: --prune-baseline requires a full-package "
                  "run (no explicit paths)")
            return 1
        entries = load_baseline(args.baseline)
        kept = [e for e in entries if e in live_keys]
        _write_baseline_keys(args.baseline, kept)
        print(f"shardcheck: pruned {len(entries) - len(kept)} dead "
              f"baseline entries ({len(kept)} kept) in {args.baseline}")
        return 0
    baseline = set() if args.no_baseline else \
        set(load_baseline(args.baseline))
    fresh = []
    for f in findings:
        if f.key() in baseline:
            _stats["baselined"] += 1
        else:
            fresh.append(f)
    for f in fresh:
        print(f.render())
    # full-package runs also gate on DEAD baseline entries: a fixed
    # finding must leave the baseline (--prune-baseline removes it),
    # otherwise the grandfather list silently grows stale and can
    # resurrect a regression unnoticed. Partial-path runs skip this —
    # entries for unscanned files would read as falsely dead.
    dead: List[tuple] = []
    if not args.paths and not args.no_baseline:
        dead = sorted(baseline - live_keys)
        for rule, file, func, text in dead:
            where = f" (in {func})" if func else ""
            print(f"{file}: [{rule}] DEAD baseline entry — the finding "
                  f"no longer fires{where}; run --prune-baseline"
                  f"\n    {text}")
    n_base = len(findings) - len(fresh)
    print(f"shardcheck: {_stats['files']} files, "
          f"{len(findings)} findings "
          f"({n_base} baselined, {_stats['suppressed']} suppressed "
          f"inline, {len(fresh)} new"
          + (f", {len(dead)} dead baseline entries" if dead else "")
          + ")")
    return 1 if fresh or dead else 0
