"""Environment-driven configuration flags.

TPU-native analogue of the reference engine's env-flag system
(reference: bodo/__init__.py:109-236 — ~30 BODO_* flags read once at import
into module globals). We keep the same "read once, module-global" model but
expose a typed dataclass so tests can override via `set_config`.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("0", "false", "no", "off", "")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v not in (None, "") else default


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


@dataclass
class Config:
    # -- execution -----------------------------------------------------------
    # Rows per streaming batch fed through the pipeline executor (analogue of
    # the reference's bodosql_streaming_batch_size, bodo/__init__.py:114).
    streaming_batch_size: int = field(
        default_factory=lambda: _env_int("BODO_TPU_STREAMING_BATCH_SIZE", 1 << 22)
    )
    # Streaming batch executor: batch-at-a-time pipelines with bounded
    # device memory (plan/streaming.py). Off by default; whole-table
    # execution is faster when everything fits in device memory.
    stream_exec: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_STREAM_EXEC", False)
    )
    # Whole-stage fusion (plan/fusion.py): compile maximal chains of
    # adjacent pipeline-compatible plan nodes (filter/project, with an
    # optional dense-aggregate root) into ONE jitted/shard_map program,
    # so intermediate tables never materialize and per-node host syncs
    # (filter count reads, rebuckets) collapse into a single group-exit
    # sync. Off → every node dispatches its own kernel (pre-fusion
    # behavior, also the fallback for non-fusable expressions).
    fusion: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_FUSION", True)
    )
    # Fused join groups (plan/fusion_join.py): extend whole-stage fusion
    # across join-probe and shuffle boundaries — the probe (and any
    # filter/project chain around it, plus an optional terminal dense
    # aggregate) compiles into ONE jit/shard_map program over a
    # device-resident build-side hash table, with the bucket shuffle's
    # lax.all_to_all traced INSIDE the program. Off → joins dispatch
    # per-operator (pre-PR-12 behavior); requires `fusion` too.
    fusion_join: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_FUSION_JOIN", True)
    )
    # Device-resident build-side hash tables kept per process (LRU):
    # each entry pins the build table's encoded key codes + slot-owner
    # LUT on device so repeat probes (streaming batches, reused build
    # subplans) skip the build entirely.
    join_build_cache_size: int = field(
        default_factory=lambda: _env_int("BODO_TPU_JOIN_BUILD_CACHE", 32)
    )
    # Pad table capacities up to a multiple of this (TPU lane friendliness).
    capacity_round: int = field(
        default_factory=lambda: _env_int("BODO_TPU_CAPACITY_ROUND", 128)
    )
    # Re-bucket a table's physical capacity when occupancy falls below this.
    rebucket_threshold: float = field(
        default_factory=lambda: _env_float("BODO_TPU_REBUCKET_THRESHOLD", 0.45)
    )
    # Mesh axis used for row sharding.
    data_axis: str = field(default_factory=lambda: _env_str("BODO_TPU_DATA_AXIS", "d"))
    # Max compiled kernels pinned per kernel cache (LRU eviction beyond
    # this — unbounded pinning exhausts XLA:CPU JIT code memory and
    # segfaults the compiler after thousands of distinct compilations).
    kernel_cache_size: int = field(
        default_factory=lambda: _env_int("BODO_TPU_KERNEL_CACHE_SIZE", 512)
    )
    # Skew headroom factor for all_to_all shuffle bucket capacity.
    shuffle_skew_factor: float = field(
        default_factory=lambda: _env_float("BODO_TPU_SHUFFLE_SKEW", 2.0)
    )
    # Dense (sort-free) groupby: when the exact product of key ranges is at
    # most this many slots, rows scatter straight into dense slots and all
    # aggregations are one segment pass (no lax.sort). ~4M slots * 8B * a
    # few columns of transient dense arrays.
    dense_groupby_max_slots: int = field(
        default_factory=lambda: _env_int("BODO_TPU_DENSE_GROUPBY_SLOTS",
                                         1 << 22)
    )
    # Scatter-claim hash groupby/join (ops/hashtable.py): sort-free
    # group ids / join LUTs at arbitrary key cardinality.
    hash_groupby: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_HASH_GROUPBY", True)
    )
    hash_join: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_HASH_JOIN", True)
    )
    # Dense-LUT join: build sides whose key-range product is at most this
    # many slots (and whose keys are unique) join by perfect-hash gather.
    dense_join_max_slots: int = field(
        default_factory=lambda: _env_int("BODO_TPU_DENSE_JOIN_SLOTS",
                                         1 << 22)
    )
    # Broadcast-join threshold: build side smaller than this many rows is
    # all_gather'd instead of hash-shuffled (analogue of broadcast join,
    # reference bodo/libs/_shuffle.h:153-210).
    bcast_join_threshold: int = field(
        default_factory=lambda: _env_int("BODO_TPU_BCAST_JOIN_THRESHOLD", 1 << 20)
    )
    # Sources with fewer rows stay replicated (broadcast-join heuristic);
    # larger ones are row-sharded over the mesh.
    shard_min_rows: int = field(
        default_factory=lambda: _env_int("BODO_TPU_SHARD_MIN_ROWS", 100_000)
    )
    # -- pipelined I/O (runtime/io_pool.py) ----------------------------------
    # Batches decoded ahead of the consumer by the streaming sources'
    # Prefetcher (batch k+1 decodes on a host thread while batch k runs
    # on the device). 0 disables prefetching entirely. The effective
    # depth derates under memory-governor pressure (depth x batch bytes
    # is admission-charged against the derived budget).
    prefetch_depth: int = field(
        default_factory=lambda: _env_int("BODO_TPU_PREFETCH_DEPTH", 2)
    )
    # Workers in the shared I/O thread pool used for parallel parquet
    # row-group decode and CSV chunk parse. <= 0 means auto:
    # min(8, cpu_count), at least 2 (Arrow releases the GIL, so decode
    # overlaps file I/O even on one core).
    io_threads: int = field(
        default_factory=lambda: _env_int("BODO_TPU_IO_THREADS", 0)
    )
    # Device-side parquet decode (io/device_decode.py): pool workers
    # ship raw page bytes and jitted XLA programs decode PLAIN
    # fixed-width / dictionary / RLE-bool pages and definition levels
    # directly into device buffers. Columns whose encoding the device
    # programs don't cover (DELTA_*, BYTE_STREAM_SPLIT, non-dict
    # strings, nested) transparently fall back to the host pyarrow
    # decode per column. Off -> every page decodes on host (pre-PR 9
    # behavior).
    device_decode: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_DEVICE_DECODE", True)
    )
    # Minimum estimated decoded size (uncompressed bytes, from footer
    # row-group metadata) before a read takes the device route. Tiny
    # reads decode faster on host than the program dispatch costs, and
    # every distinct page shape pins an XLA executable — not worth it
    # below ~1 MiB. 0 -> always take the device route when enabled.
    device_decode_min_bytes: int = field(
        default_factory=lambda: _env_int(
            "BODO_TPU_DEVICE_DECODE_MIN_BYTES", 1 << 20)
    )
    # Total wall-clock budget (seconds) for the bench accelerator probe
    # across ALL retry attempts; <= 0 means the per-attempt
    # timeout x attempts product is the only cap. Guards against the
    # r05-style retry storm (6 x 75s timeouts before CPU fallback).
    bench_probe_budget_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_BENCH_PROBE_BUDGET",
                                           150.0)
    )
    # -- frontend ------------------------------------------------------------
    # Fall back to real pandas for unsupported args (reference:
    # bodo/pandas/utils.py:346 check_args_fallback).
    pandas_fallback: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_PANDAS_FALLBACK", True)
    )
    warn_fallback: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_WARN_FALLBACK", True)
    )
    # Dump optimized plans (analogue BODO_DATAFRAME_LIBRARY_DUMP_PLANS).
    dump_plans: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_DUMP_PLANS", False)
    )
    # -- observability -------------------------------------------------------
    # 0 = silent, 1 = pushdown/fallback notices, 2 = plan dumps, 3 = kernel trace
    # (analogue of bodo.set_verbose_level, bodo/user_logging.py:1-40).
    verbose_level: int = field(
        default_factory=lambda: _env_int("BODO_TPU_VERBOSE_LEVEL", 0)
    )
    tracing_level: int = field(
        default_factory=lambda: _env_int("BODO_TPU_TRACING_LEVEL", 0)
    )
    # Ring-buffer capacity for trace events (drop-oldest beyond this;
    # dropped events are counted — long-running sessions can't leak).
    trace_events_max: int = field(
        default_factory=lambda: _env_int("BODO_TPU_TRACE_EVENTS_MAX",
                                         100_000)
    )
    # When set, gang runs write the merged multi-rank chrome trace here
    # (trace_gang_<ts>.json); also inherited by spawned workers.
    trace_dir: str = field(
        default_factory=lambda: _env_str("BODO_TPU_TRACE_DIR", "")
    )
    # Communication observatory (parallel/comm.py): per-collective
    # bytes/wall/peer-wait accounting at every host-level dispatch site.
    # On by default — the accounting is a dict update per DISPATCH (not
    # per element); bench.py --suite comm pins the overhead < 2%.
    comm_accounting: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_COMM_ACCOUNTING",
                                          True)
    )
    # -- telemetry / flight recorder (runtime/telemetry.py) ------------------
    # Background sampler: one daemon thread snapshotting subsystem stats
    # (governor occupancy, io queue depth, fusion cache, lockstep head,
    # heartbeat age, RSS) into a bounded ring every interval. The knob
    # gates whether ensure_sampler() actually starts the thread; it is
    # called from init_runtime(), spawned workers, and serve().
    telemetry: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_TELEMETRY", True)
    )
    telemetry_interval_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_TELEMETRY_INTERVAL",
                                           1.0)
    )
    # Ring capacity (samples kept in memory; 600 x 1s = 10 min window).
    telemetry_ring: int = field(
        default_factory=lambda: _env_int("BODO_TPU_TELEMETRY_RING", 600)
    )
    # HTTP endpoint port for /metrics + /healthz + /debug/flightrecorder
    # (-1 = no server; 0 = bind an ephemeral port). The server is
    # started by telemetry.serve() / init_runtime(), never at import.
    telemetry_port: int = field(
        default_factory=lambda: _env_int("BODO_TPU_TELEMETRY_PORT", -1)
    )
    # Flight recorder: dump a self-contained diagnostic bundle on gang
    # failure, LockstepError, or SIGUSR1.
    flight_recorder: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_FLIGHT_RECORDER",
                                          True)
    )
    # Bundle destination; empty -> <tempdir>/bodo_tpu_flightrec.
    flight_dir: str = field(
        default_factory=lambda: _env_str("BODO_TPU_FLIGHT_DIR", "")
    )
    # Slowest-N EXPLAIN ANALYZE records embedded per bundle.
    flight_slow_queries: int = field(
        default_factory=lambda: _env_int("BODO_TPU_FLIGHT_SLOW_QUERIES",
                                         5)
    )
    # -- numerics ------------------------------------------------------------
    # Use bfloat16 accumulation for mean/var where tolerable (perf knob).
    low_precision_agg: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_LOW_PRECISION_AGG", False)
    )
    # Pack small-range multi-key groupby/sort keys into one int64 (big
    # sort/shuffle win; disable to force the general lexicographic path).
    pack_keys: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_PACK_KEYS", True)
    )
    # Streaming device-state budget in MiB (0 = unbounded). When a
    # streaming sort/join's accumulated device state exceeds this, the
    # state is sorted/parked to the spillable host pool via the
    # comptroller (larger-than-HBM streaming; reference analogue:
    # OperatorBufferPool spill thresholds, bodo/libs/_operator_pool.h).
    stream_device_budget_mb: int = field(
        default_factory=lambda: _env_int(
            "BODO_TPU_STREAM_DEVICE_BUDGET_MB", 0)
    )
    # Memory governor (runtime/memory_governor.py): derive a real device
    # budget at mesh init and govern every state-materializing operator
    # against it — admission control, forced spill mode, OOM-retry.
    # When stream_device_budget_mb is set it wins (exact legacy
    # behavior); the governor is the default when nothing is pinned.
    mem_governor: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_MEM_GOVERNOR", True)
    )
    # Fraction of the probed device memory reserved as headroom (XLA
    # scratch, fragmentation, transient shuffle buffers).
    mem_headroom_frac: float = field(
        default_factory=lambda: _env_float("BODO_TPU_MEM_HEADROOM", 0.15)
    )
    # Largest slice of the derived budget a single operator may hold as
    # device-resident state before its grant forces partitioned/spill
    # mode (the reference's per-operator budget negotiation).
    mem_op_fraction: float = field(
        default_factory=lambda: _env_float("BODO_TPU_MEM_OP_FRACTION", 0.5)
    )
    # -- adaptive query execution (plan/adaptive.py) -------------------------
    # Observe actual cardinalities at stage boundaries and correct the
    # remaining plan: broadcast promote/demote against governor budgets,
    # hot-key splits before all_to_all shuffles, undersized streaming-batch
    # coalescing, and mid-plan join re-ordering on observed rows.
    aqe: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_AQE", True)
    )
    # Broadcast-join byte budget: replicating a build side is allowed while
    # its observed device bytes stay under this fraction of the governor's
    # derived per-device budget. Larger builds demote to a shuffle join;
    # smaller ones promote to broadcast even when the rows-based
    # bcast_join_threshold planned a shuffle.
    aqe_bcast_frac: float = field(
        default_factory=lambda: _env_float("BODO_TPU_AQE_BCAST_FRAC", 0.05)
    )
    # A sampled join/shuffle key owning at least this fraction of rows is
    # "hot": its rows split off and broadcast-join so the all_to_all only
    # carries the cold remainder.
    aqe_skew_frac: float = field(
        default_factory=lambda: _env_float("BODO_TPU_AQE_SKEW_FRAC", 0.3)
    )
    # Probe sides smaller than this skip skew detection (sampling costs
    # more than any skew it could find).
    aqe_skew_min_rows: int = field(
        default_factory=lambda: _env_int("BODO_TPU_AQE_SKEW_MIN_ROWS",
                                         100_000)
    )
    # Streaming batches filled below this fraction of the nominal batch
    # size merge with their successors before the next per-batch kernel.
    aqe_coalesce_frac: float = field(
        default_factory=lambda: _env_float("BODO_TPU_AQE_COALESCE_FRAC",
                                           0.25)
    )
    # Persistent runtime-stats store directory (runtime/stats_store.py):
    # observed cardinalities keyed by normalized subplan fingerprints, so
    # repeated queries start from observed rather than guessed stats.
    # Empty = in-process observations only (no persistence).
    stats_store_dir: str = field(
        default_factory=lambda: _env_str("BODO_TPU_STATS_DIR", "")
    )
    # Persistent XLA compilation cache directory (the @jit(cache=True)
    # analogue — reference: Numba on-disk JIT cache, caching_tests/).
    # Set to a path to survive process restarts; empty disables. Applied
    # at import and again by set_config(compile_cache_dir=...).
    compile_cache_dir: str = field(
        default_factory=lambda: _env_str("BODO_TPU_COMPILE_CACHE_DIR", "")
    )
    # SQL plan cache directory (analogue BODO_SQL_PLAN_CACHE_DIR).
    sql_plan_cache_dir: str = field(
        default_factory=lambda: _env_str("BODO_TPU_SQL_PLAN_CACHE_DIR", "")
    )
    # -- semantic result cache (runtime/result_cache.py) ---------------------
    # Cache executed results keyed by (structural plan fingerprint,
    # dataset signature) and maintain them incrementally under
    # append-only dataset growth. Off -> no cross-query result reuse at
    # all (per-plan node memoization still applies).
    result_cache: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_RESULT_CACHE", True)
    )
    # Device-byte budget for cached results. 0 = auto: a fraction of
    # the memory governor's derived device budget (floor 64 MiB).
    result_cache_bytes: int = field(
        default_factory=lambda: _env_int("BODO_TPU_RESULT_CACHE_BYTES", 0)
    )
    # Host-side spill tier: entries evicted under device pressure move
    # to host pandas instead of being dropped, and rehydrate on hit.
    result_cache_host_spill: bool = field(
        default_factory=lambda: _env_bool(
            "BODO_TPU_RESULT_CACHE_HOST_SPILL", True)
    )
    # Byte cap of the host spill tier (0 disables the tier outright).
    result_cache_host_bytes: int = field(
        default_factory=lambda: _env_int(
            "BODO_TPU_RESULT_CACHE_HOST_BYTES", 1 << 28)
    )
    # -- query serving (runtime/scheduler.py, bodo_tpu.serve) ----------------
    # Worker threads draining the per-session queues onto the gang. One
    # worker serializes queries (an SPMD gang runs one program at a
    # time anyway); more overlap host-side planning/IO of one query
    # with device execution of another.
    serve_workers: int = field(
        default_factory=lambda: _env_int("BODO_TPU_SERVE_WORKERS", 1)
    )
    # Per-session bounded queue depth; overflow is a typed Overloaded
    # rejection with a retry-after hint, never an unbounded buffer.
    serve_queue_depth: int = field(
        default_factory=lambda: _env_int("BODO_TPU_SERVE_QUEUE_DEPTH", 32)
    )
    # Total queued requests across all sessions before global shedding.
    serve_max_pending: int = field(
        default_factory=lambda: _env_int("BODO_TPU_SERVE_MAX_PENDING",
                                         256)
    )
    # Admission control from live health/metrics signals (off = every
    # submit is admitted; bounded queues still backpressure).
    serve_admission: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_SERVE_ADMISSION",
                                          True)
    )
    # Governor occupancy (granted / derived budget) at which new work
    # is shed with Overloaded instead of risking OOM.
    serve_shed_occupancy: float = field(
        default_factory=lambda: _env_float(
            "BODO_TPU_SERVE_SHED_OCCUPANCY", 0.92)
    )
    # Gang comm wait fraction above which comm-wait-dominated sessions
    # (their own EWMA also above this) are backed off.
    serve_comm_wait_frac: float = field(
        default_factory=lambda: _env_float(
            "BODO_TPU_SERVE_COMM_WAIT_FRAC", 0.5)
    )
    # Priority aging rate: every this-many seconds a session's head
    # request has waited discounts one second of its accrued virtual
    # time, bounding starvation of low-weight sessions.
    serve_aging_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_SERVE_AGING_S", 5.0)
    )
    # Base retry-after hint (seconds) attached to typed rejections
    # (scaled up by rejection severity and measured queue wait).
    serve_retry_after_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_SERVE_RETRY_AFTER",
                                           0.25)
    )
    # Latency-bound SLO class: priority aging runs this many times
    # faster for slo="latency" sessions, so their queued requests
    # overtake throughput-bound traffic without starving it.
    serve_latency_boost: float = field(
        default_factory=lambda: _env_float(
            "BODO_TPU_SERVE_LATENCY_BOOST", 4.0)
    )
    # -- fleet serving (runtime/fleet.py, bodo_tpu.fleet) ---------------------
    # Stable identity of THIS gang process within a fleet. Set by the
    # fleet controller in each gang's environment; empty outside fleet
    # mode. Exported on set_config so result-cache ownership, metric
    # labels and flight-recorder manifests all see the same id.
    gang_id: str = field(
        default_factory=lambda: _env_str("BODO_TPU_GANG_ID", "")
    )
    # TCP port for the controller's client listener (-1 = in-process
    # controller only, no listener; 0 = ephemeral).
    fleet_port: int = field(
        default_factory=lambda: _env_int("BODO_TPU_FLEET_PORT", -1)
    )
    # Default gang count for fleet.start() when none is given.
    fleet_gangs: int = field(
        default_factory=lambda: _env_int("BODO_TPU_FLEET_GANGS", 2)
    )
    # Controller scrape cadence of each gang's /metrics + /healthz.
    fleet_scrape_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_FLEET_SCRAPE_S", 0.5)
    )
    # Hard cap on a single wire-protocol frame body; an oversized
    # header is a typed ProtocolError, never an attempted allocation.
    fleet_frame_max: int = field(
        default_factory=lambda: _env_int("BODO_TPU_FLEET_FRAME_MAX",
                                         64 << 20)
    )
    # Cache peering: on a local result-cache miss the owning gang asks
    # the fingerprint's previous owner before recomputing.
    fleet_peering: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_FLEET_PEERING", True)
    )
    # Per-session in-flight quota at the controller; overflow is a
    # typed Overloaded(reason="session_quota"), not an unbounded pile.
    fleet_session_quota: int = field(
        default_factory=lambda: _env_int("BODO_TPU_FLEET_SESSION_QUOTA",
                                         64)
    )
    # Consecutive failed scrapes before a gang is declared dead and
    # evicted from the routing ring.
    fleet_dead_scrapes: int = field(
        default_factory=lambda: _env_int("BODO_TPU_FLEET_DEAD_SCRAPES", 3)
    )
    # -- materialized views (runtime/views.py) -------------------------------
    # Base signature-watcher poll interval for continuous queries; a
    # subscription's max_staleness_s tightens the effective interval
    # (poll at most every max_staleness_s/4, floored at 50 ms).
    view_poll_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_VIEW_POLL_S", 1.0)
    )
    # Weighted-fair priority of the system maintenance session view
    # refreshes run under (tenants are not billed for shared refreshes;
    # < 1.0 keeps maintenance from starving interactive traffic).
    view_maintenance_weight: float = field(
        default_factory=lambda: _env_float("BODO_TPU_VIEW_MAINT_WEIGHT",
                                           0.5)
    )
    # Per-source-file contribution maps (partition-level invalidation)
    # are built only for datasets of at most this many files — the map
    # costs one extra pass over the dataset per materialization.
    view_max_parts: int = field(
        default_factory=lambda: _env_int("BODO_TPU_VIEW_MAX_PARTS", 64)
    )
    # -- resilience (runtime/resilience.py) ----------------------------------
    # Armed fault-injection spec (see resilience module docstring for the
    # grammar, e.g. "io.read=raise:OSError,collective=raise:Internal:1:0").
    # set_config(faults=...) arms in-process AND exports BODO_TPU_FAULTS
    # so spawned workers inherit the same chaos.
    faults: str = field(
        default_factory=lambda: _env_str("BODO_TPU_FAULTS", "")
    )
    # Retry envelope: attempts / base backoff / overall deadline for
    # transient errors (coordination-service init, filesystem flake,
    # RESOURCE_EXHAUSTED outside the stage envelope).
    retry_attempts: int = field(
        default_factory=lambda: _env_int("BODO_TPU_RETRY_ATTEMPTS", 3)
    )
    retry_base_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_RETRY_BASE_S", 0.05)
    )
    retry_deadline_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_RETRY_DEADLINE_S",
                                           30.0)
    )
    # Graceful degradation: when a sharded collective fails with a
    # non-OOM internal error, re-execute the stage replicated (gather
    # inputs, run the REP kernel path) instead of failing the query.
    degrade_replicated: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_DEGRADE_REPLICATED",
                                          True)
    )
    # Spawn supervision: worker heartbeat cadence and the staleness
    # window after which a silent-but-alive rank is declared hung.
    spawn_hb_interval_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_SPAWN_HB_INTERVAL",
                                           0.5)
    )
    spawn_hb_timeout_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_SPAWN_HB_TIMEOUT",
                                           15.0)
    )
    # Gang-level retries of run_spmd when ALL failing ranks look
    # transient (coordination-service init flake).
    spawn_gang_retries: int = field(
        default_factory=lambda: _env_int("BODO_TPU_SPAWN_GANG_RETRIES", 1)
    )
    # -- elastic gangs (runtime/elastic.py) ----------------------------------
    # Master switch for stage-checkpointed shrink-grow recovery: stage
    # boundaries register checkpoints, a lost rank shrinks the mesh and
    # resumes the plan suffix, and the scheduler resumes (not fails)
    # queries that raise a RankLost. set_config(elastic=...) exports
    # BODO_TPU_ELASTIC so spawned workers inherit the posture.
    elastic: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_ELASTIC", True)
    )
    # Shared checkpoint/control directory for elastic gang runs (the
    # launcher points this at each gang's temp dir; empty = the run's
    # own gang dir only).
    elastic_dir: str = field(
        default_factory=lambda: _env_str("BODO_TPU_ELASTIC_DIR", "")
    )
    # Checkpoint-store byte bound per process (shards beyond the
    # committed frontier are pruned after every commit; resident bytes
    # are charged to the memory governor through an advisory grant).
    elastic_ckpt_bytes: int = field(
        default_factory=lambda: _env_int("BODO_TPU_ELASTIC_CKPT_BYTES",
                                         256 << 20)
    )
    # How many shrinks one gang run may absorb, and the smallest mesh
    # recovery may shrink to before falling back to gang-level retry.
    elastic_max_shrinks: int = field(
        default_factory=lambda: _env_int("BODO_TPU_ELASTIC_MAX_SHRINKS", 2)
    )
    elastic_min_ranks: int = field(
        default_factory=lambda: _env_int("BODO_TPU_ELASTIC_MIN_RANKS", 1)
    )
    # Whole-gang retries after elastic recovery itself fails (a fault
    # during re-mesh must fall back to the existing gang-level retry).
    elastic_gang_retries: int = field(
        default_factory=lambda: _env_int("BODO_TPU_ELASTIC_GANG_RETRIES",
                                         1)
    )
    # Straggler-eviction policy: a rank whose checkpoint frontier trails
    # its peers and has not advanced for this long is evicted like a
    # dead one (0 = never evict stragglers). Attribution prefers the
    # comm observatory's lockstep arrival stamps when available.
    elastic_straggler_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_ELASTIC_STRAGGLER_S",
                                           0.0)
    )
    # Grace given to an evicted-but-alive rank to exit clean before the
    # parent tears it down (its state stays "evicted" either way).
    elastic_evict_grace_s: float = field(
        default_factory=lambda: _env_float(
            "BODO_TPU_ELASTIC_EVICT_GRACE_S", 2.0)
    )
    # Background grow path: re-admit replacement capacity (a joiner
    # rank at the next stage boundary of a shrunk run; full width at
    # the next query boundary in serving).
    elastic_grow: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_ELASTIC_GROW", True)
    )
    # Re-form the jax.distributed cluster on the post-shrink mesh (real
    # pods). Off by default: the recovery shuffle moves state through
    # the shared gang dir and must not depend on collectives; the CPU
    # backend has no cross-process collectives to re-form anyway.
    elastic_remesh_distributed: bool = field(
        default_factory=lambda: _env_bool(
            "BODO_TPU_ELASTIC_REMESH_DISTRIBUTED", False)
    )
    # -- shardcheck / SPMD safety (analysis/) --------------------------------
    # Validate every logical plan against the distribution/shape
    # invariants before execution (analysis/plan_validator.py).
    # Violations raise PlanInvariantError instead of wrong answers or a
    # wedged gang; cost is one host-side DFS per plan.
    plan_validate: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_PLAN_VALIDATE", True)
    )
    # Lockstep debug mode (analysis/lockstep.py): fingerprint every
    # host-level collective dispatch and cross-check sequence/site
    # against peer processes, converting divergent control flow into a
    # structured LockstepError in seconds instead of a gang hang.
    # set_config(lockstep=...) exports BODO_TPU_LOCKSTEP so spawned
    # workers inherit the mode.
    lockstep: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_LOCKSTEP", False)
    )
    # Shared directory for the per-rank dispatch logs (spawn.py points
    # this at each gang's fresh temp dir; multi-process runs without it
    # disable checking with a warning).
    lockstep_dir: str = field(
        default_factory=lambda: _env_str("BODO_TPU_LOCKSTEP_DIR", "")
    )
    # How long a rank waits for its peers to reach the same dispatch
    # sequence number before declaring divergence.
    lockstep_timeout_s: float = field(
        default_factory=lambda: _env_float("BODO_TPU_LOCKSTEP_TIMEOUT",
                                           10.0)
    )
    # progcheck (analysis/progcheck.py): jaxpr-level verification of
    # every registered program — collective-manifest extraction +
    # rank-invariance, donation/aliasing audit, static HBM peak
    # estimation. Default on (one trace walk per distinct program);
    # violations warn-and-record unless progcheck_enforce raises them
    # as ProgramInvariantError at registration. set_config exports both
    # so spawned workers inherit the posture.
    progcheck: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_PROGCHECK", True)
    )
    progcheck_enforce: bool = field(
        default_factory=lambda: _env_bool("BODO_TPU_PROGCHECK_ENFORCE",
                                          False)
    )


config = Config()


def set_config(**kwargs) -> None:
    """Override config values at runtime (tests / notebooks)."""
    valid = {f.name for f in fields(Config)}
    for k, v in kwargs.items():
        if k not in valid:
            raise ValueError(f"unknown config key: {k}")
        setattr(config, k, v)
        if k == "faults":
            # arm in-process AND export to the environment so spawned
            # workers (which copy os.environ) inherit the same chaos
            from bodo_tpu.runtime import resilience
            resilience.arm(v or "")
            if v:
                os.environ["BODO_TPU_FAULTS"] = v
            else:
                os.environ.pop("BODO_TPU_FAULTS", None)
        if k == "compile_cache_dir" and v:
            import jax
            jax.config.update("jax_compilation_cache_dir", v)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.1)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0)
            try:
                # jax latches cache-in-use on the FIRST compile of the
                # process; without a reset, enabling the dir after any
                # compile has happened is silently a no-op
                from jax._src import compilation_cache
                compilation_cache.reset_cache()
            except Exception:
                pass
            from bodo_tpu.utils import tracing
            tracing.install_compile_cache_listener()
        if k == "io_threads":
            # drop the shared executor so the next I/O rebuilds it at
            # the new width
            from bodo_tpu.runtime import io_pool
            io_pool.reset_pool()
        if k in ("result_cache", "result_cache_bytes",
                 "result_cache_host_spill", "result_cache_host_bytes"):
            # re-apply budgets to a live cache (lazy: never imports the
            # module just to reconfigure it); disabling drops entries
            import sys as _sys
            rc = _sys.modules.get("bodo_tpu.runtime.result_cache")
            if rc is not None:
                rc.reconfigure()
        if k.startswith("serve_"):
            # re-size a live scheduler's worker pool / drop its signal
            # snapshot (lazy: never imports the module to reconfigure)
            import sys as _sys
            sch = _sys.modules.get("bodo_tpu.runtime.scheduler")
            if sch is not None:
                sch.reconfigure()
        if k == "gang_id":
            # export like faults so result-cache ownership, metric
            # labels and spawned sub-workers see the same identity
            if v:
                os.environ["BODO_TPU_GANG_ID"] = v
            else:
                os.environ.pop("BODO_TPU_GANG_ID", None)
        if k.startswith("fleet_"):
            # re-apply knobs to a live controller (lazy: never imports
            # the module just to reconfigure it)
            import sys as _sys
            fl = _sys.modules.get("bodo_tpu.runtime.fleet")
            if fl is not None:
                fl.reconfigure()
        if k.startswith("elastic"):
            # export like faults/lockstep so spawned gang workers
            # inherit the recovery posture and checkpoint budget
            env_name = "BODO_TPU_" + k.upper()
            if isinstance(v, bool):
                os.environ[env_name] = "1" if v else "0"
            elif v in ("", None):
                os.environ.pop(env_name, None)
            else:
                os.environ[env_name] = str(v)
        if k == "stats_store_dir":
            # flush + drop the open store so the next lookup re-binds to
            # the new directory
            from bodo_tpu.runtime import stats_store
            stats_store.reset_store()
        if k in ("lockstep", "lockstep_dir", "lockstep_timeout_s"):
            # drop the live checker so the next dispatch rebinds to the
            # new mode/dir; export the env (like faults) so spawned
            # workers inherit the debug mode
            from bodo_tpu.analysis import lockstep as _lockstep
            _lockstep.reset()
            if k == "lockstep":
                if v:
                    os.environ["BODO_TPU_LOCKSTEP"] = "1"
                else:
                    os.environ.pop("BODO_TPU_LOCKSTEP", None)
            if k == "lockstep_dir":
                if v:
                    os.environ["BODO_TPU_LOCKSTEP_DIR"] = v
                else:
                    os.environ.pop("BODO_TPU_LOCKSTEP_DIR", None)
        if k in ("progcheck", "progcheck_enforce"):
            # export like lockstep so spawned workers inherit the
            # verification posture
            env_name = "BODO_TPU_" + k.upper()
            if v:
                os.environ[env_name] = "1"
            else:
                os.environ.pop(env_name, None)
        if k == "trace_events_max":
            # rebuild the ring buffer at the new capacity (keeps the
            # newest events)
            from bodo_tpu.utils import tracing
            tracing.resize_events_buffer()
        if k == "trace_dir":
            # export like faults/lockstep so spawned workers inherit it
            if v:
                os.environ["BODO_TPU_TRACE_DIR"] = v
            else:
                os.environ.pop("BODO_TPU_TRACE_DIR", None)
        if k in ("telemetry", "telemetry_interval_s", "flight_recorder",
                 "flight_dir"):
            # export like faults/lockstep/trace_dir so spawned workers
            # inherit the telemetry + flight-recorder posture
            env_name = {
                "telemetry": "BODO_TPU_TELEMETRY",
                "telemetry_interval_s": "BODO_TPU_TELEMETRY_INTERVAL",
                "flight_recorder": "BODO_TPU_FLIGHT_RECORDER",
                "flight_dir": "BODO_TPU_FLIGHT_DIR",
            }[k]
            if isinstance(v, bool):
                os.environ[env_name] = "1" if v else "0"
            elif v in ("", None):
                os.environ.pop(env_name, None)
            else:
                os.environ[env_name] = str(v)
            if k in ("telemetry", "telemetry_interval_s"):
                # rebind a live sampler to the new gate/period (lazy:
                # never imports the module just to reconfigure it)
                import sys as _sys
                tl = _sys.modules.get("bodo_tpu.runtime.telemetry")
                if tl is not None:
                    tl.reconfigure()


def set_verbose_level(level: int) -> None:
    config.verbose_level = int(level)
