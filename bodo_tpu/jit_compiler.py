"""@jit — the drop-in decorator surface (reference bodo/decorators.py:338).

The reference compiles pandas-using Python bytecode via Numba into MPI SPMD
binaries. A bytecode compiler is the wrong tool for a trace-to-XLA stack
(SURVEY.md §7: "the lazy-plan design is much better suited to tracing"), so
@jit here is a *tracer*: the function runs once per call with pandas entry
points redirected to the lazy frontend — dataframe arguments become lazy
frames, `pd.read_parquet`/`read_csv`/`merge`/... build plan nodes, and the
optimized plan executes on the mesh. Results materialize back to pandas,
matching the reference's calling convention (real results on the caller).

Numeric-array functions skip the dataframe layer entirely and go straight
to jax.jit (the parfor/array path of the reference).

Flags accepted for parity (reference Flags, decorators.py:57): distributed,
replicated, returns_maybe_distributed, cache — distribution hints map onto
shard/REP placement; cache maps onto XLA's compilation cache.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Optional, Sequence

import numpy as np
import pandas as pd


import threading

_redirect_tls = threading.local()          # .depth: per-thread jit nesting
_redirect_lock = threading.Lock()
_redirect_active = [0]                     # process-wide active redirects
_redirect_originals: dict = {}


def _in_jit() -> bool:
    return getattr(_redirect_tls, "depth", 0) > 0


class _PandasRedirect:
    """Context that redirects pandas module-level entry points used inside
    jitted functions to the lazy frontend (read_parquet/read_csv/merge).
    Unsupported kwargs route to the genuine pandas function (host read)
    with a fallback warning instead of being silently dropped.

    The installed wrappers are THREAD-AWARE: only the thread(s) currently
    inside a jitted call see the redirect; concurrent host pandas use
    from other threads reaches the genuine functions (the reference has
    no such hazard because its JIT rewrites call sites at compile time
    rather than patching the module)."""

    _PATCHED = ("read_parquet", "read_csv", "merge")

    def __enter__(self):
        with _redirect_lock:
            if _redirect_active[0] == 0:
                self._install()
            _redirect_active[0] += 1
        _redirect_tls.depth = getattr(_redirect_tls, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _redirect_tls.depth -= 1
        with _redirect_lock:
            _redirect_active[0] -= 1
            if _redirect_active[0] == 0:
                for n, f in _redirect_originals.items():
                    setattr(pd, n, f)
                _redirect_originals.clear()
        return False

    @staticmethod
    def _install():
        import bodo_tpu.pandas_api as bd
        from bodo_tpu.utils.logging import warn_fallback
        orig = {n: getattr(pd, n) for n in _PandasRedirect._PATCHED}
        # _install is only reached from __enter__ with _redirect_lock
        # held (the refcount gate above it) — the lint can't see callers
        # shardcheck: ignore[unlocked-shared-state]
        _redirect_originals.update(orig)

        def _read_parquet(path, **kw):
            if not _in_jit():
                return orig["read_parquet"](path, **kw)
            extra = set(kw) - {"columns", "engine"}
            if extra:  # unsupported kwargs → genuine pandas (host) read
                warn_fallback("jit pd.read_parquet", f"kwargs {sorted(extra)}")
                return bd.from_pandas(orig["read_parquet"](path, **kw))
            return bd.read_parquet(path, columns=kw.get("columns"))
        pd.read_parquet = _read_parquet

        def _read_csv(path, **kw):
            if not _in_jit():
                return orig["read_csv"](path, **kw)
            extra = set(kw) - {"usecols", "parse_dates"}
            if extra:
                warn_fallback("jit pd.read_csv", f"kwargs {sorted(extra)}")
                return bd.from_pandas(orig["read_csv"](path, **kw))
            return bd.read_csv(path, columns=kw.get("usecols"),
                               parse_dates=kw.get("parse_dates"))
        pd.read_csv = _read_csv

        def _merge(left, right, **kw):
            if not _in_jit():
                return orig["merge"](left, right, **kw)
            l_ = bd.from_pandas(left) if isinstance(left, pd.DataFrame) else left
            r_ = bd.from_pandas(right) if isinstance(right, pd.DataFrame) \
                else right
            try:
                return l_.merge(r_, **kw)
            except TypeError:  # unsupported merge kwargs → host pandas
                warn_fallback("jit pd.merge", f"kwargs {sorted(kw)}")
                lp = left if isinstance(left, pd.DataFrame) else left.to_pandas()
                rp = right if isinstance(right, pd.DataFrame) \
                    else right.to_pandas()
                return bd.from_pandas(orig["merge"](lp, rp, **kw))
        pd.merge = _merge


def _is_numeric_args(args, kwargs) -> bool:
    vals = list(args) + list(kwargs.values())
    if not vals:
        return False
    import jax
    ok = (np.ndarray, jax.Array, int, float, complex, bool, np.generic)
    return all(isinstance(v, ok) for v in vals)


def jit(fn: Optional[Callable] = None, *, distributed=None, replicated=None,
        returns_maybe_distributed=None, cache: bool = False, spawn=None,
        args_maybe_distributed=None, **flags):
    """Decorate a function for distributed execution (reference
    bodo/decorators.py:338 `jit`). See module docstring for semantics."""
    if fn is None:
        return lambda f: jit(f, distributed=distributed,
                             replicated=replicated, cache=cache, **flags)

    import jax
    jax_jitted = None

    numeric_ok = True  # flips off if the fn turns out to use pandas inside

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        nonlocal jax_jitted, numeric_ok
        # pure numeric path → straight jax.jit; functions that use pandas
        # internally fail this trace and permanently take the frame path.
        # Only trace/type failures trigger the fallback — genuine runtime
        # errors in user code (assertions, ZeroDivisionError, ...) propagate
        # rather than silently re-executing via the frame path.
        if numeric_ok and _is_numeric_args(args, kwargs):
            try:
                if jax_jitted is None:
                    # one jit per user-@jit-decorated function,
                    # bounded by the program text itself
                    # shardcheck: ignore[unregistered-jit]
                    jax_jitted = jax.jit(fn)
                out = jax_jitted(*args, **kwargs)
                return jax.tree.map(
                    lambda x: np.asarray(x) if isinstance(x, jax.Array) else x,
                    out)
            except (TypeError, ValueError, IndexError, AttributeError,
                    NotImplementedError) as e:
                # JAXTypeError (tracer leaks, concretization) subclasses
                # TypeError and NonConcreteBooleanIndexError subclasses
                # IndexError; ValueError/AttributeError cover shape and
                # duck-typing failures of pandas-flavored code on arrays.
                # Errors outside these (AssertionError, ZeroDivisionError,
                # KeyError...) are genuine user bugs and propagate.
                from bodo_tpu.utils.logging import warn_fallback
                warn_fallback(getattr(fn, "__name__", "jit"),
                              f"numeric jax.jit path failed, using the "
                              f"dataframe path: {type(e).__name__}: {e}")
                numeric_ok = False
                jax_jitted = None

        # dataframe path → trace through the lazy frontend
        import bodo_tpu.pandas_api as bd
        from bodo_tpu.pandas_api.frame import BodoDataFrame
        from bodo_tpu.pandas_api.series import BodoSeries

        def lift(v):
            if isinstance(v, pd.DataFrame):
                return bd.from_pandas(v)
            return v

        def lower(v):
            if isinstance(v, BodoDataFrame):
                return v.to_pandas()
            if isinstance(v, BodoSeries):
                return v.to_pandas()
            if isinstance(v, tuple):
                return tuple(lower(x) for x in v)
            if isinstance(v, list):
                return [lower(x) for x in v]
            if isinstance(v, dict):
                return {k: lower(x) for k, x in v.items()}
            return v

        with _PandasRedirect():
            out = fn(*[lift(a) for a in args],
                     **{k: lift(v) for k, v in kwargs.items()})
        return lower(out)

    wrapper.__bodo_tpu_jit__ = True
    return wrapper


def wrap_python(fn: Callable) -> Callable:
    """Host-callback escape hatch (reference bodo/decorators.py:582
    `wrap_python`): the wrapped function always runs as plain Python on
    host data. Inside device UDF compilation it becomes a
    jax.pure_callback; at the frontend level it simply marks the function
    as fallback-only."""
    fn.__bodo_tpu_wrap_python__ = True
    return fn
