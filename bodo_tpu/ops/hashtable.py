"""XLA scatter-claim hash table: arbitrary-cardinality group ids and
join LUTs without sorting.

TPU-native replacement for the reference's serial-chaining hash tables
(bodo/libs/_hash_join.cpp, bodo/libs/groupby/_groupby.cpp): instead of
per-row insert chains, all rows claim table slots IN PARALLEL with a
scatter-min, and unresolved rows re-probe in lock-step rounds (double
hashing). Every round is a handful of dense scatters/gathers — exactly
the ops XLA lowers well on TPU — and the expected round count at load
factor ≤ 0.5 is small (longest probe chain, O(log U)).

The claim table is sized 2×capacity so no cardinality estimate and no
overflow retry is needed; the table itself is one int32 array (the
claiming row id per slot), so its memory cost is 8 bytes/row. Group ids
are then re-densified to [0, n_groups) with a cumsum so downstream
segment-reductions run over a capacity-sized space, not the table.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bodo_tpu.ops import sort_encoding as SE
from bodo_tpu.utils.kernel_cache import bounded_jit

# murmur3 fmix64 constants — the standard 64-bit avalanche finalizer
_M1 = np.uint64(0xFF51AFD7ED558CCD)
_M2 = np.uint64(0xC4CEB9FE1A85EC53)
_GOLD = np.uint64(0x9E3779B97F4A7C15)  # 2^64/phi, for multi-key combine

# rows that fail to resolve within this many probe rounds trigger the
# caller's sort-based fallback (practically unreachable at load 0.5)
MAX_ROUNDS = 64


def _fmix64(x):
    x = x ^ (x >> np.uint64(33))
    x = x * _M1
    x = x ^ (x >> np.uint64(33))
    x = x * _M2
    return x ^ (x >> np.uint64(33))


def combine_hash(codes: Sequence) -> jax.Array:
    """One uint64 hash per row from bijective per-column uint64 codes."""
    h = jnp.full(codes[0].shape, np.uint64(0x5851F42D4C957F2D))
    for c in codes:
        h = _fmix64(h ^ c) + _GOLD
    return _fmix64(h)


def encode_columns(key_arrays: Sequence[Tuple], null_equal: bool = True):
    """(codes, ok) for hashing/equality.

    codes: one bijective uint64 per key column; when `null_equal`, nulls
    get a dedicated extra 0/1 code column (null == null, and no real
    value can collide with the null group). When not `null_equal`,
    null-keyed rows are excluded via `ok` (pandas groupby dropna /
    SQL join semantics)."""
    codes = []
    ok = None
    for data, valid in key_arrays:
        enc = SE.encode_value(data)
        null = SE.null_flag(data, valid)
        if null is not None:
            if null_equal:
                codes.append(null.astype(jnp.uint64))
                enc = jnp.where(null, np.uint64(0), enc)
            else:
                nn = ~null
                ok = nn if ok is None else (ok & nn)
        codes.append(enc)
    return tuple(codes), ok


def encode_columns_aligned(key_arrays: Sequence[Tuple],
                           null_cols: Sequence[bool],
                           null_equal: bool = True):
    """Like encode_columns, but with a caller-fixed per-key null-column
    layout so two sides of a join encode to STRUCTURALLY IDENTICAL code
    tuples even when only one side is nullable. `null_cols[i]` is True
    when key i gets a null code column (must be the OR of both sides'
    nullability)."""
    codes = []
    ok = None
    for (data, valid), want_null in zip(key_arrays, null_cols):
        enc = SE.encode_value(data)
        null = SE.null_flag(data, valid)
        if null is None and want_null:
            null = jnp.zeros(data.shape, bool)
        if null is not None:
            if null_equal:
                codes.append(null.astype(jnp.uint64))
                enc = jnp.where(null, np.uint64(0), enc)
            else:
                nn = ~null
                ok = nn if ok is None else (ok & nn)
        codes.append(enc)
    return tuple(codes), ok


def aligned_codes(probe_keys: Sequence[Tuple], build_keys: Sequence[Tuple],
                  null_equal: bool):
    """Encode two positionally-aligned key sets into STRUCTURALLY
    IDENTICAL code tuples: build keys cast to the probe dtypes, and both
    sides share one null-column layout (the OR of their nullability).
    The one spelling of the hash-join/membership encode used by
    ops/join.py `_hash_gids` and the streaming drain's key-membership
    probe. Returns (pcodes, bcodes, p_ok, b_ok) with ok = None when no
    rows are excluded."""
    bkeys = tuple((bd.astype(pd_.dtype), bv)
                  for (pd_, _pv), (bd, bv) in zip(probe_keys, build_keys))
    null_cols = tuple(
        SE.null_flag(pd_, pv) is not None
        or SE.null_flag(bd, bv) is not None
        for (pd_, pv), (bd, bv) in zip(probe_keys, bkeys))
    bcodes, b_ok = encode_columns_aligned(bkeys, null_cols, null_equal)
    pcodes, p_ok = encode_columns_aligned(probe_keys, null_cols,
                                          null_equal)
    return pcodes, bcodes, p_ok, b_ok


def table_size(capacity: int) -> int:
    """Power-of-two claim-table size at load factor ≤ 0.5."""
    t = 16
    while t < 2 * max(capacity, 1):
        t <<= 1
    return t


@bounded_jit(static_argnames=("T", "max_rounds"))
def claim_slots(codes: Tuple, ok, T: int, max_rounds: int = MAX_ROUNDS):
    """Assign every ok row a slot in [0, T): equal keys share a slot,
    distinct keys get distinct slots.

    Returns (slot int32[N] (-1 for !ok), owner int32[T] (claiming row id
    per slot, -1 empty), rounds_used int32, unresolved bool — True means
    some row never resolved (caller must fall back)."""
    n = codes[0].shape[0]
    mask = np.uint64(T - 1)
    h = combine_hash(codes)
    # odd step → the probe sequence cycles through all T slots
    step = (_fmix64(h ^ _GOLD) | np.uint64(1)) & mask
    h = h & mask
    rows = jnp.arange(n, dtype=jnp.int32)
    big = jnp.int32(np.iinfo(np.int32).max)

    def cond(state):
        r, slot, owner = state
        return (r < max_rounds) & jnp.any(slot == -1)

    def body(state):
        r, slot, owner = state
        un = slot == -1
        p = ((h + r.astype(jnp.uint64) * step) & mask).astype(jnp.int32)
        # claim: the smallest probing row id wins each still-empty slot
        cand = jnp.where(un, rows, big)
        claim = jnp.full(T, big, jnp.int32).at[p].min(cand)
        owner = jnp.where((owner < 0) & (claim < big),
                          claim, owner)
        # match: probing rows whose slot owner holds an equal key resolve
        o = owner[p]
        osafe = jnp.maximum(o, 0)
        eq = o >= 0
        for c in codes:
            eq = eq & (c[osafe] == c)
        slot = jnp.where(un & eq, p, slot)
        return r + jnp.uint32(1), slot, owner

    slot0 = jnp.where(ok, jnp.int32(-1), jnp.int32(-2))
    owner0 = jnp.full(T, -1, jnp.int32)
    r, slot, owner = lax.while_loop(
        cond, body, (jnp.uint32(0), slot0, owner0))
    unresolved = jnp.any(slot == -1)
    # drop slots claimed only by rows that later resolved elsewhere is
    # impossible: a slot's owner resolves TO that slot in the round it
    # claims (it matches itself), so every owned slot is a live group
    return jnp.where(slot < 0, -1, slot), owner, r, unresolved


@bounded_jit(static_argnames=("T",))
def densify(slot, owner, T: int):
    """Map claim-table slots to dense group ids [0, n_groups).

    Returns (seg int32[N] — dense group id per row, group id = n for
    !ok rows; group_row int32[cap] — a representative source row per
    dense group id, packed at the front; n_groups)."""
    n = slot.shape[0]
    present = owner >= 0
    newid = (jnp.cumsum(present.astype(jnp.int32)) - 1)
    n_groups = newid[-1] + 1
    seg = jnp.where(slot >= 0, newid[jnp.maximum(slot, 0)], n)
    # representative row per dense group (scatter; ids are unique)
    group_row = jnp.full(n, -1, jnp.int32).at[
        jnp.where(present, newid, n)].set(
        jnp.maximum(owner, 0), mode="drop")
    return seg, group_row, n_groups


def group_ids(key_arrays: Sequence[Tuple], ok_rows,
              max_rounds: int = MAX_ROUNDS):
    """End-to-end: dense pandas-dropna group ids for arbitrary keys.

    key_arrays: [(data, valid), ...]; ok_rows: bool[cap] live-row mask.
    Returns (seg int32[cap] in [0, n_groups) (== cap for dropped rows),
    group_row int32[cap], n_groups, unresolved)."""
    codes, null_ok = encode_columns(key_arrays, null_equal=False)
    ok = ok_rows if null_ok is None else (ok_rows & null_ok)
    cap = codes[0].shape[0]
    T = table_size(cap)
    slot, owner, _r, unresolved = claim_slots(codes, ok, T, max_rounds)
    seg, group_row, n_groups = densify(slot, owner, T)
    return seg, group_row, n_groups, unresolved


# ---------------------------------------------------------------------------
# hash join LUT (unique build keys; dup-build falls back to sort-merge)
# ---------------------------------------------------------------------------

@bounded_jit(static_argnames=("T", "max_rounds"))
def probe_slots(build_codes: Tuple, owner, probe_codes: Tuple, ok,
                T: int, max_rounds: int = MAX_ROUNDS):
    """For each probe row, the build row with an equal key, else -1.

    Follows the same double-hash probe sequence as claim_slots; a probe
    terminates on key match (hit) or empty slot (miss). Returns
    (idx int32[M], unresolved bool)."""
    m = probe_codes[0].shape[0]
    mask = np.uint64(T - 1)
    h = combine_hash(probe_codes)
    step = (_fmix64(h ^ _GOLD) | np.uint64(1)) & mask
    h = h & mask

    # pallas route: the whole probe walk as one kernel (slot gather +
    # 64-bit key compare on the MXU). Gate read at trace time — tests
    # flipping FORCE_INTERPRET clear probe_slots.cache.
    from bodo_tpu.ops import pallas_kernels as PK
    res = PK.hash_probe(build_codes, owner, probe_codes, ok, h, step,
                        T, max_rounds)
    if res is not None:
        return res

    def cond(state):
        r, idx, active = state
        return (r < max_rounds) & jnp.any(active)

    def body(state):
        r, idx, active = state
        p = ((h + r.astype(jnp.uint64) * step) & mask).astype(jnp.int32)
        o = owner[p]
        osafe = jnp.maximum(o, 0)
        eq = o >= 0
        for bc, pc in zip(build_codes, probe_codes):
            eq = eq & (bc[osafe] == pc)
        hit = active & eq
        miss = active & (o < 0)
        idx = jnp.where(hit, o, idx)
        active = active & ~hit & ~miss
        return r + jnp.uint32(1), idx, active

    idx0 = jnp.full(m, -1, jnp.int32)
    r, idx, active = lax.while_loop(
        cond, body, (jnp.uint32(0), idx0, ok))
    return idx, jnp.any(active)
