"""Window kernels: cumulative ops, rolling windows, shift/diff.

TPU-native replacement for the reference's parallel window machinery
(bodo/hiframes/rolling.py halo exchange via bodo.libs.parallel_ops,
bodo/libs/window/*.cpp, dist_cumsum via MPI_Exscan
bodo/libs/distributed_api.py:2205). Cross-shard state rides collectives:
cumulative offsets via exscan (all_gather + masked reduce), rolling halos
via lax.ppermute ring shifts (SURVEY.md §5 long-context analogue — the
ring-attention-style blockwise pass applied to windowed aggregation).

All kernels are local-block functions taking (x, valid, count) plus the
cross-shard carry; the shard_map wrapper lives in relational.py.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bodo_tpu.ops import kernels as K
from bodo_tpu.parallel import collectives
from bodo_tpu.utils.kernel_cache import bounded_jit


def _ok(x, valid, padmask):
    return K.value_ok(x, valid, padmask)


# ---------------------------------------------------------------------------
# cumulative ops: local part + carry combine
# ---------------------------------------------------------------------------

_CUM_NEUTRAL = {"cumsum": 0.0, "cumprod": 1.0,
                "cummax": -np.inf, "cummin": np.inf}


def cum_local(op: str, x, valid, count):
    """Returns (local result, local carry scalar). Result positions of
    null rows are NaN (pandas semantics); padding rows are neutral."""
    cap = x.shape[0]
    padmask = K.row_mask(count, cap)
    ok = _ok(x, valid, padmask)
    xf = x.astype(jnp.float64)
    if op == "cumsum":
        base = jnp.where(ok, xf, 0.0)
        loc = jnp.cumsum(base)
        carry = loc[-1]
    elif op == "cumprod":
        base = jnp.where(ok, xf, 1.0)
        loc = jnp.cumprod(base)
        carry = loc[-1]
    elif op == "cummax":
        base = jnp.where(ok, xf, -jnp.inf)
        loc = lax.cummax(base)
        carry = loc[-1]
    elif op == "cummin":
        base = jnp.where(ok, xf, jnp.inf)
        loc = lax.cummin(base)
        carry = loc[-1]
    else:
        raise ValueError(op)
    return loc, carry


def cum_combine(op: str, loc, carry_prefix):
    """Apply the exscan'd prefix carry from earlier shards."""
    if op == "cumsum":
        return loc + carry_prefix
    if op == "cumprod":
        return loc * carry_prefix
    if op == "cummax":
        return jnp.maximum(loc, carry_prefix)
    if op == "cummin":
        return jnp.minimum(loc, carry_prefix)
    raise ValueError(op)


def cum_carry_exscan(op: str, carry, axis: str):
    """Exclusive scan of carries over shards (identity for shard 0)."""
    n = collectives.axis_size(axis)
    idx = lax.axis_index(axis)
    gathered = lax.all_gather(carry, axis)          # [S]
    mask = jnp.arange(n) < idx
    ident = _CUM_NEUTRAL[op]
    vals = jnp.where(mask, gathered, ident)
    if op == "cumsum":
        return jnp.sum(vals)
    if op == "cumprod":
        return jnp.prod(vals)
    if op == "cummax":
        return jnp.max(vals)
    if op == "cummin":
        return jnp.min(vals)
    raise ValueError(op)


def cum_finalize(op: str, combined, x, valid, count):
    """NaN at null positions, zeros at padding."""
    cap = x.shape[0]
    padmask = K.row_mask(count, cap)
    ok = _ok(x, valid, padmask)
    return jnp.where(ok, combined, jnp.where(padmask, jnp.nan, 0.0))


# ---------------------------------------------------------------------------
# rolling windows (fixed window w, min_periods = w — pandas default)
# ---------------------------------------------------------------------------

def rolling_local(op: str, window: int, x, valid, count, halo_x, halo_ok,
                  global_offset):
    """Rolling over the local block with a (window-1)-row halo from the
    previous shard. halo_x/halo_ok: [window-1] values/validity from the
    end of the previous shard's real rows; global_offset: number of real
    rows before this shard (positions < window-1 globally are NaN)."""
    cap = x.shape[0]
    w = window
    padmask = K.row_mask(count, cap)
    ok = _ok(x, valid, padmask)
    xf = jnp.where(ok, x.astype(jnp.float64), 0.0)
    ext = jnp.concatenate([jnp.where(halo_ok, halo_x, 0.0), xf])
    ext_ok = jnp.concatenate([halo_ok, ok])

    if op in ("sum", "mean"):
        cs = jnp.cumsum(ext)
        cs0 = jnp.concatenate([jnp.zeros(1), cs])
        out = cs0[w:] - cs0[:-w]          # [cap]: sum over ext[i..i+w-1]
    elif op in ("min", "max"):
        # sparse-table doubling: O(log w) shifted reductions instead of an
        # O(w) unroll (which explodes trace size for large windows)
        ident = jnp.inf if op == "min" else -jnp.inf
        red = jnp.minimum if op == "min" else jnp.maximum
        level = jnp.where(ext_ok, ext, ident)
        span = 1
        while span * 2 <= w:
            level = red(level, jnp.concatenate(
                [level[span:], jnp.full((span,), ident)]))
            span *= 2
        # window [i, i+w) = block [i, i+span) ∪ block [i+w-span, i+w)
        lead = jnp.concatenate([level[w - span:],
                                jnp.full((w - span,), ident)]) \
            if w > span else level
        out = red(level, lead)[:cap]
    elif op == "count":
        cs = jnp.cumsum(ext_ok.astype(jnp.float64))
        cs0 = jnp.concatenate([jnp.zeros(1), cs])
        out = cs0[w:] - cs0[:-w]
    else:
        raise ValueError(op)

    okc = jnp.cumsum(ext_ok.astype(jnp.int64))
    okc0 = jnp.concatenate([jnp.zeros(1, jnp.int64), okc])
    nvalid = okc0[w:] - okc0[:-w]
    if op == "mean":
        out = out / jnp.maximum(nvalid, 1)
    gpos = global_offset + jnp.arange(cap)
    full = (nvalid == w) & (gpos >= w - 1) & padmask
    if op == "count":
        # pandas >= 1.3: count obeys min_periods=window like other aggs
        full_pos = (gpos >= w - 1) & padmask
        return jnp.where(full_pos, out, jnp.where(padmask, jnp.nan, 0.0))
    return jnp.where(full, out, jnp.where(padmask, jnp.nan, 0.0))


def tail_rows(x, valid, count, k: int):
    """Last k real rows of the block (for the halo send): values + ok."""
    cap = x.shape[0]
    idx = jnp.clip(count - k + jnp.arange(k), 0, cap - 1)
    have = (count - k + jnp.arange(k)) >= 0
    padmask = K.row_mask(count, cap)
    ok = _ok(x, valid, padmask)
    return (jnp.where(have, x.astype(jnp.float64)[idx], 0.0),
            have & ok[idx])


def multi_hop_halo(x, valid, count, k: int, axis: str):
    """Last k rows across ALL predecessor shards (not just the immediate
    neighbour): every shard all-gathers its k-row tail, and each shard
    selects the trailing k rows among shards before it. Row EXISTENCE
    (position past padding) is tracked separately from value validity —
    a null predecessor row still occupies its halo slot so shift/rolling
    see its null, exactly as a local previous row would. Handles short
    and empty predecessor shards — the case that used to force a gather
    fallback. Cost: one all_gather of [S, k] doubles + flags."""
    cap = x.shape[0]
    idx = jnp.clip(count - k + jnp.arange(k), 0, cap - 1)
    exists = (count - k + jnp.arange(k)) >= 0          # row present
    padmask = K.row_mask(count, cap)
    okv = _ok(x, valid, padmask)
    tx = jnp.where(exists, x.astype(jnp.float64)[idx], 0.0)
    tok = exists & okv[idx]                            # value also valid
    all_tx = lax.all_gather(tx, axis)                  # [S, k]
    all_tex = lax.all_gather(exists, axis)
    all_tok = lax.all_gather(tok, axis)
    S = all_tx.shape[0]
    r = lax.axis_index(axis)
    shard_ids = jnp.repeat(jnp.arange(S), k)     # [S*k], shard of each row
    flat_x = all_tx.reshape(-1)
    flat_ex = all_tex.reshape(-1) & (shard_ids < r)
    flat_ok = all_tok.reshape(-1) & (shard_ids < r)
    # j-th existing row counted from the END goes to halo slot k - j
    rev = jnp.cumsum(flat_ex[::-1])[::-1]
    slot = jnp.where(flat_ex & (rev <= k), k - rev, k)  # k = dropped
    halo_x = jnp.zeros(k, flat_x.dtype).at[slot].set(flat_x, mode="drop")
    halo_ok = jnp.zeros(k, bool).at[slot].set(flat_ok, mode="drop")
    return halo_x, halo_ok


def prev_last_value(x, valid, count, axis: str):
    """The last real row's (value, value_ok, exists) from the nearest
    non-empty predecessor shard, in the ORIGINAL dtype (no float64
    round-trip — int64 ticks stay exact). Used for cross-shard tie
    detection in global ranking."""
    cap = x.shape[0]
    last_i = jnp.clip(count - 1, 0, cap - 1)
    lv = x[last_i]
    padmask = K.row_mask(count, cap)
    lok = _ok(x, valid, padmask)[last_i] & (count > 0)
    have = count > 0
    all_v = lax.all_gather(lv, axis)         # [S]
    all_ok = lax.all_gather(lok, axis)
    all_have = lax.all_gather(have, axis)
    S = all_v.shape[0]
    r = lax.axis_index(axis)
    ids = jnp.arange(S)
    cand = all_have & (ids < r)
    best = jnp.max(jnp.where(cand, ids, -1))
    exists = best >= 0
    sel = jnp.clip(best, 0, S - 1)
    return all_v[sel], all_ok[sel] & exists, exists


# ---------------------------------------------------------------------------
# shift / diff
# ---------------------------------------------------------------------------

def shift_local(x, valid, count, halo_x, halo_ok, n: int):
    """Shift by n>0 (from previous rows; halo has the last n rows of the
    previous shard). Returns (data, ok)."""
    cap = x.shape[0]
    padmask = K.row_mask(count, cap)
    ok = _ok(x, valid, padmask)
    ext = jnp.concatenate([halo_x, x.astype(jnp.float64)])
    ext_ok = jnp.concatenate([halo_ok, ok])
    out = ext[:cap]
    out_ok = ext_ok[:cap] & padmask
    return jnp.where(out_ok, out, jnp.nan), out_ok


# ---------------------------------------------------------------------------
# partitioned ranking windows: ROW_NUMBER / RANK / DENSE_RANK / NTILE /
# CUMCOUNT over (PARTITION BY keys ORDER BY order_cols)
# ---------------------------------------------------------------------------

@bounded_jit(static_argnames=("specs", "num_keys", "ascending",
                                   "na_last"))
def rank_window_local(key_arrays, order_arrays, count,
                      specs: Tuple[Tuple[str, int], ...], num_keys: int,
                      ascending: Tuple[bool, ...] = (),
                      na_last: bool = True):
    """Ranking window functions in one sorted pass.

    TPU-native replacement for the reference's window-function family
    (bodo/libs/window/_window_aggfuncs.cpp, _window_calculator.cpp):
    stable sort by (partition keys, order cols), segment boundaries from
    key changes, then each rank flavor is an elementwise/scan expression
    over segment-relative positions; results scatter back to the input
    row order. specs: (op, param) with op in row_number/rank/dense_rank/
    ntile/cumcount; param is ntile's bucket count.

    Null partition keys form their own partition (SQL semantics: NULLs
    group together in PARTITION BY). Returns int64 outputs aligned with
    input rows (0 on padding rows).
    """
    cap = key_arrays[0][0].shape[0] if key_arrays else \
        order_arrays[0][0].shape[0]
    (perm, padmask_s, seg, seg_start, seg_end, seg_cnt_row, newval,
     peer_end, pos) = _sorted_segments(key_arrays, order_arrays, count,
                                       ascending, na_last, cap)
    n_segs = cap
    row_no = pos - seg_start + 1                          # 1-based
    dense = jnp.cumsum(newval & padmask_s)
    dense_rank = dense - jax.ops.segment_min(
        jnp.where(padmask_s, dense, cap + 1), seg, num_segments=n_segs
    )[seg] + 1
    # rank: row_number of the first row with an equal order value
    first_eq = jnp.where(newval, pos, 0)
    first_eq = jax.lax.cummax(first_eq)                   # last change point
    rank = first_eq - seg_start + 1

    outs_sorted = []
    for op, param in specs:
        if op == "row_number":
            o = row_no
        elif op == "cumcount":
            o = row_no - 1
        elif op == "rank":
            o = rank
        elif op == "dense_rank":
            o = dense_rank
        elif op == "ntile":
            # SQL NTILE: first (cnt mod n) buckets get ceil(cnt/n) rows,
            # the rest floor(cnt/n) (ref _window_aggfuncs.cpp ntile)
            if int(param) < 1:
                raise ValueError(
                    f"NTILE argument must be positive, got {param}")
            n = jnp.int64(param)
            cnt = jnp.maximum(seg_cnt_row, 1)
            small = cnt // n
            rem = cnt - small * n
            big_rows = rem * (small + 1)       # rows in the big buckets
            r0 = row_no - 1
            o = jnp.where(
                r0 < big_rows,
                r0 // (small + 1) + 1,
                rem + (r0 - big_rows) // jnp.maximum(small, 1) + 1)
        else:
            raise ValueError(f"unknown rank window op: {op}")
        outs_sorted.append(jnp.where(padmask_s, o, 0).astype(jnp.int64))

    # scatter back to input row order
    inv = jnp.zeros(cap, dtype=jnp.int64).at[perm].set(pos)
    return tuple(o[inv] for o in outs_sorted)


# ---------------------------------------------------------------------------
# partitioned aggregate windows: SUM/AVG/MIN/MAX/COUNT ... OVER
# (PARTITION BY k ORDER BY o [ROWS BETWEEN a AND b]) + LEAD/LAG +
# FIRST_VALUE/LAST_VALUE
# ---------------------------------------------------------------------------

def _sorted_segments(key_arrays, order_arrays, count, ascending, na_last,
                     cap: int):
    """Shared sort/segment machinery for ALL partitioned window kernels:
    stable sort by (partition keys, order cols); partition boundaries
    from null-canonicalized key changes (a null — mask or NaN — compares
    equal to another null, never to a value; raw NaN != NaN would split
    every null row into its own group). Returns per-row arrays in sorted
    order: (perm, padmask_s, seg, seg_start, seg_end, seg_cnt_row,
    newval, peer_end, pos)."""
    from bodo_tpu.ops import kernels as K
    from bodo_tpu.ops import sort_encoding as SE

    padmask = K.row_mask(count, cap)
    operands: list = []
    for d, v in key_arrays:
        # partition nulls group together: use the null rank slot but keep
        # them, padding rows still sort last
        operands.extend(SE.key_operands(d, v, padmask=padmask))
    if not ascending:
        ascending = tuple(True for _ in order_arrays)
    for (d, v), asc in zip(order_arrays, ascending):
        operands.extend(SE.key_operands(d, v, ascending=asc,
                                        na_last=na_last, padmask=padmask))
    nko = len(operands)
    operands.append(jnp.arange(cap))
    perm = lax.sort(tuple(operands), num_keys=max(nko, 1),
                    is_stable=True)[-1]
    padmask_s = padmask[perm]
    pos = jnp.arange(cap)

    def _changes(arrays):
        chg = jnp.zeros(cap, dtype=bool)
        for d, v in arrays:
            null = SE.null_flag(d, v)
            ds = d[perm]
            if null is not None:
                ns = null[perm]
                ds = jnp.where(ns, jnp.zeros((), d.dtype), ds)
                chg = chg | (ns != jnp.roll(ns, 1))
            chg = chg | (ds != jnp.roll(ds, 1))
        return chg

    newpart = (_changes(key_arrays) & padmask_s) | (pos == 0)
    seg = jnp.maximum(jnp.cumsum(newpart) - 1, 0)
    n_segs = cap
    seg_start = jax.ops.segment_min(jnp.where(padmask_s, pos, cap), seg,
                                    num_segments=n_segs)[seg]
    seg_cnt_row = jax.ops.segment_sum(padmask_s.astype(jnp.int64), seg,
                                      num_segments=n_segs)[seg]
    seg_end = seg_start + seg_cnt_row - 1
    # peer groups: rows equal on ALL order keys (RANGE frame boundary)
    newval = newpart | (_changes(order_arrays) & padmask_s)
    peer = jnp.cumsum(newval & padmask_s)
    peer_end = jax.ops.segment_max(jnp.where(padmask_s, pos, -1), peer,
                                   num_segments=cap + 1)[peer]
    return (perm, padmask_s, seg, seg_start, seg_end, seg_cnt_row,
            newval, peer_end, pos)


def _minmax_sparse_table(x_masked, n_levels: int, want_max: bool):
    """Sparse-table levels for range-min/max queries: levels[k][i] =
    red(x[i .. i+2^k-1]) (array-clamped; queries stay inside segments so
    no segment masking is needed at build time). Works in the value's own
    domain dtype (int64 for integers/datetimes/decimals, float for
    floats) so results are EXACT — no float64 round-trip."""
    red = jnp.maximum if want_max else jnp.minimum
    cap = x_masked.shape[0]
    levels = [x_masked]
    span = 1
    for _ in range(n_levels - 1):
        prev = levels[-1]
        idx = jnp.minimum(jnp.arange(cap) + span, cap - 1)
        levels.append(red(prev, prev[idx]))
        span *= 2
    return jnp.stack(levels)  # [K, cap]


def _range_minmax(levels, a, b, empty, want_max: bool, sentinel):
    """min/max over [a, b] per row from sparse-table levels ([K, cap]).

    floor(log2(length)) is computed by a static unrolled compare chain
    over the (few) levels — no frexp/bitcast, which the TPU x64-rewrite
    pass rejects."""
    length = jnp.maximum(b - a + 1, 1)
    n_levels = levels.shape[0]
    k = jnp.zeros(length.shape, dtype=jnp.int32)
    for j in range(1, n_levels):
        k = jnp.where(length >= (1 << j), j, k)
    cap = levels.shape[1]
    left = levels[k, jnp.clip(a, 0, cap - 1)]
    right = levels[k, jnp.clip(b - (1 << jnp.clip(k, 0, 62)) + 1,
                               0, cap - 1)]
    red = jnp.maximum if want_max else jnp.minimum
    out = red(left, right)
    return jnp.where(empty, sentinel, out)


@bounded_jit(static_argnames=("specs", "num_keys", "ascending",
                                   "na_last"))
def agg_window_local(key_arrays, order_arrays, val_arrays, count,
                     specs: Tuple, num_keys: int,
                     ascending: Tuple[bool, ...] = (),
                     na_last: bool = True):
    """Aggregate/navigation window functions in one sorted pass.

    TPU-native replacement for the reference's window aggregate family
    (bodo/libs/window/_window_aggfuncs.cpp WindowAggfunc,
    bodo/libs/_lead_lag.cpp): sort once by (partition, order) keys, then
    every frame aggregate is a prefix-sum difference (sum/count/mean) or
    a sparse-table range query (min/max) over the sorted array —
    O(n log n) total, no per-row loops, MXU/VPU-friendly static shapes.

    specs: tuple of (op, val_idx, frame, param):
      op    ∈ sum/mean/count/min/max/lead/lag/first_value/last_value
      frame ∈ ("all",)                — whole partition (no ORDER BY)
              ("cumrange",)           — RANGE UNBOUNDED PRECEDING..CURRENT
                                        ROW (ORDER BY default; peers incl.)
              ("rows", lo, hi)        — ROWS BETWEEN frames; lo/hi are
                                        row offsets (None = unbounded)
      param — LEAD/LAG offset (ignored otherwise)

    Returns one (data, valid_bool) pair per spec, aligned with input
    rows: prefix-sum ops (sum/mean/count) in float64; min/max in the
    value's exact domain (int64 for ints/datetimes/decimals, float64 for
    floats); gather ops (lead/lag/first/last) in the SOURCE dtype so
    dictionary codes and datetimes survive."""
    from bodo_tpu.ops import kernels as K

    cap = (key_arrays[0][0].shape[0] if key_arrays
           else (order_arrays[0][0].shape[0] if order_arrays
                 else val_arrays[0][0].shape[0]))
    (perm, padmask_s, seg, seg_start, seg_end, _seg_cnt, _newval,
     peer_end, pos) = _sorted_segments(key_arrays, order_arrays, count,
                                       ascending, na_last, cap)
    padmask = K.row_mask(count, cap)

    # per-value-column sorted data, ok masks, prefix sums (built lazily)
    sorted_cache: dict = {}

    def _sorted_val(vi):
        if vi not in sorted_cache:
            d, v = val_arrays[vi]
            ok = K.value_ok(d, v, padmask)
            sorted_cache[vi] = (d[perm], ok[perm])
        return sorted_cache[vi]

    prefix_cache: dict = {}

    def _prefixes(vi):
        if vi not in prefix_cache:
            ds, oks = _sorted_val(vi)
            xf = jnp.where(oks, ds.astype(jnp.float64), 0.0)
            P0 = jnp.concatenate([jnp.zeros(1), jnp.cumsum(xf)])
            C0 = jnp.concatenate([jnp.zeros(1, jnp.int64),
                                  jnp.cumsum(oks.astype(jnp.int64))])
            prefix_cache[vi] = (P0, C0)
        return prefix_cache[vi]

    n_levels = max(int(np.ceil(np.log2(max(cap, 2)))) + 1, 1)
    table_cache: dict = {}

    def _tables(vi, want_max: bool):
        """Sparse table + sentinel in the value's exact domain: floats
        stay float (widened to f64), everything else (ints, bools,
        datetime ticks, decimal scaled-ints) runs in int64 so min/max
        round-trip exactly (large ids, timestamps, 18-digit decimals)."""
        key = (vi, want_max)
        if key not in table_cache:
            ds, oks = _sorted_val(vi)
            if jnp.issubdtype(ds.dtype, jnp.floating):
                dom = ds.astype(jnp.float64)
                sentinel = -jnp.inf if want_max else jnp.inf
            elif ds.dtype == jnp.uint64:
                # int64 would wrap values >= 2^63 negative — stay unsigned
                dom = ds
                ii = jnp.iinfo(jnp.uint64)
                sentinel = jnp.asarray(ii.min if want_max else ii.max,
                                       dtype=jnp.uint64)
            else:
                dom = ds.astype(jnp.int64)
                ii = jnp.iinfo(jnp.int64)
                sentinel = jnp.asarray(ii.min if want_max else ii.max,
                                       dtype=jnp.int64)
            xm = jnp.where(oks, dom, sentinel)
            table_cache[key] = (
                _minmax_sparse_table(xm, n_levels, want_max), sentinel)
        return table_cache[key]

    def _frame_bounds(frame):
        if frame[0] == "all":
            return seg_start, seg_end
        if frame[0] == "cumrange":
            return seg_start, peer_end
        lo, hi = frame[1], frame[2]
        a = seg_start if lo is None else jnp.maximum(pos + lo, seg_start)
        b = seg_end if hi is None else jnp.minimum(pos + hi, seg_end)
        return a, b

    outs = []
    inv = jnp.zeros(cap, dtype=jnp.int64).at[perm].set(pos)
    for op, vi, frame, param in specs:
        if op in ("lead", "lag"):
            off = int(param) * (1 if op == "lead" else -1)
            tgt = pos + off
            ds, oks = _sorted_val(vi)
            inside = (tgt >= seg_start) & (tgt <= seg_end) & padmask_s
            safe = jnp.clip(tgt, 0, cap - 1)
            od = jnp.where(inside, ds[safe], jnp.zeros((), ds.dtype))
            ov = inside & oks[safe]
        elif op in ("first_value", "last_value"):
            a, b = _frame_bounds(frame)
            ds, oks = _sorted_val(vi)
            at = a if op == "first_value" else b
            nonempty = (b >= a) & padmask_s
            safe = jnp.clip(at, 0, cap - 1)
            od = jnp.where(nonempty, ds[safe], jnp.zeros((), ds.dtype))
            ov = nonempty & oks[safe]
        elif op in ("sum", "sum0", "mean", "count"):
            a, b = _frame_bounds(frame)
            P0, C0 = _prefixes(vi)
            a_ = jnp.clip(a, 0, cap)
            b_ = jnp.clip(b + 1, 0, cap)
            nonempty = (b >= a) & padmask_s
            wsum = jnp.where(nonempty, P0[b_] - P0[a_], 0.0)
            wcnt = jnp.where(nonempty, C0[b_] - C0[a_], 0)
            if op == "count":
                od = wcnt.astype(jnp.float64)
                ov = padmask_s
            elif op == "sum":
                od = wsum
                ov = wcnt > 0          # SQL: SUM over empty/all-null=NULL
            elif op == "sum0":
                od = wsum              # pandas: empty/all-null sums to 0
                ov = padmask_s
            else:
                od = wsum / jnp.maximum(wcnt, 1)
                ov = wcnt > 0
        elif op in ("min", "max"):
            a, b = _frame_bounds(frame)
            lv, sentinel = _tables(vi, op == "max")
            _, C0 = _prefixes(vi)
            empty = (b < a) | ~padmask_s
            m = _range_minmax(lv, a, b, empty, op == "max", sentinel)
            # validity from the non-null COUNT, not isfinite(m): a real
            # +/-inf data value must survive as inf, not become NULL
            wcnt = jnp.where(empty, 0,
                             C0[jnp.clip(b + 1, 0, cap)]
                             - C0[jnp.clip(a, 0, cap)])
            ov = wcnt > 0
            od = jnp.where(ov, m, jnp.zeros((), m.dtype))
        else:
            raise ValueError(f"unknown agg window op: {op}")
        # scatter back to input row order
        outs.append((od[inv], ov[inv]))
    return tuple(outs)
