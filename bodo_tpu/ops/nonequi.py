"""Tiled nested-loop (non-equi) join.

TPU-native replacement for the reference's nested-loop and interval
joins (reference: bodo/libs/_nested_loop_join_impl.cpp cross-product
block join, bodo/libs/_interval_join.cpp point-in-interval). The C++
row-pair loop becomes a tiled broadcast: probe rows are processed in
fixed-size tiles, each tile evaluates the join predicate on the dense
[tile x build] pair grid in one fused kernel (VPU-friendly elementwise
compare + compact), so device memory is O(tile x build), never
O(|L| x |R|). Matches are compacted to a bucketed output capacity with
a host-checked overflow retry (the same capacity discipline as the
shuffle buckets).

An interval fast path sorts the probe side by the point column and the
build side by interval start, so each probe tile only grids against the
build PREFIX whose starts precede the tile's max point — near-linear
for mostly-disjoint intervals, degrading gracefully to the full grid
under heavy overlap.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.config import config
from bodo_tpu.ops import kernels as K
from bodo_tpu.plan.expr import (BinOp, ColRef, Expr, eval_expr,
                                expr_columns)
from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.table import Column, REP, Table, round_capacity

# pair-grid budget: tile_rows * build_cap <= this (elements per pred col)
_GRID_BUDGET = 1 << 22

from bodo_tpu.utils.kernel_cache import KernelCache

_jit_cache = KernelCache(maxsize=config.kernel_cache_size,
                         subsystem="nonequi")


def _pow2(n: int) -> int:
    c = 128
    while c < n:
        c <<= 1
    return c


def _build_tile_kernel(sig, pred_key, names_l: Tuple[str, ...],
                       names_r: Tuple[str, ...], pred: Expr,
                       schema, dicts, T: int, B: int, out_cap: int,
                       want_matched: bool):
    key = ("nljoin", sig, pred_key, T, B, out_cap, want_matched)
    fn = _jit_cache.get(key)
    if fn is not None:
        return fn

    def body(ltree, tcount, rtree, rcount):
        li = jnp.arange(T * B) // B
        ri = jnp.arange(T * B) % B
        grid: Dict[str, Tuple] = {}
        need = expr_columns(pred)
        for n in names_l:
            if n in need:
                d, v = ltree[n]
                grid[n] = (d[li], None if v is None else v[li])
        for n in names_r:
            if n in need:
                d, v = rtree[n]
                grid[n] = (d[ri], None if v is None else v[ri])
        mask, mv = eval_expr(pred, grid, dicts, schema)
        if mv is not None:
            mask = mask & mv
        mask = mask & (li < tcount) & (ri < rcount)
        (ci_l, ci_r), cnt = K.compact(mask, (li, ri), out_cap)
        out: Dict[str, Tuple] = {}
        for n in names_l:
            d, v = ltree[n]
            out[n] = (d[ci_l], None if v is None else v[ci_l])
        for n in names_r:
            d, v = rtree[n]
            out[n] = (d[ci_r], None if v is None else v[ci_r])
        if want_matched:
            matched = jax.ops.segment_max(
                mask.astype(jnp.int32), li, num_segments=T).astype(bool)
            return out, cnt, matched
        return out, cnt

    fn = jax.jit(body)
    _jit_cache[key] = fn
    return fn


def nl_join_rep(left: Table, right: Table, pred: Expr,
                how: str = "inner") -> Table:
    """Nested-loop join of two replicated tables under an arbitrary
    predicate over the COMBINED (already suffix-disambiguated) columns.
    how: inner | left. Output is REP with matches in probe-major order
    (then unmatched probe rows for how=left, pandas/SQL style)."""
    assert how in ("inner", "left"), how
    from bodo_tpu import relational as R
    left = R.shrink_to_fit(left)
    right = R.shrink_to_fit(right)
    B = max(right.capacity, 1)
    T = _pow2(max(min(left.capacity, max(_GRID_BUDGET // B, 1)), 1))
    # _sig fingerprints dictionaries too: string predicates bake the
    # host dictionary LUT into the trace, so same-shaped tables with
    # different dictionaries must not share a cached kernel
    sig = (R._sig(left), R._sig(right))
    schema = {n: c.dtype for n, c in left.columns.items()}
    schema.update({n: c.dtype for n, c in right.columns.items()})
    dicts = {n: c.dictionary for n, c in left.columns.items()
             if c.dictionary is not None}
    dicts.update({n: c.dictionary for n, c in right.columns.items()
                  if c.dictionary is not None})
    names_l = tuple(left.names)
    names_r = tuple(right.names)
    rtree = right.device_data()
    rcount = jnp.asarray(right.nrows)

    parts: List[Table] = []
    matched_host: List[np.ndarray] = []
    out_cap = _pow2(T)  # ~1 match per probe row to start
    n_tiles = max(1, -(-left.nrows // T)) if left.nrows else 0
    for ti in range(n_tiles):
        lo = ti * T
        tile_rows = min(T, left.nrows - lo)
        ltree = {}
        for n in names_l:
            c = left.columns[n]
            d = jax.lax.dynamic_slice_in_dim(c.data, lo, T) \
                if left.capacity >= lo + T else \
                jnp.pad(c.data[lo:], (0, T - (left.capacity - lo)))
            v = None
            if c.valid is not None:
                v = jax.lax.dynamic_slice_in_dim(c.valid, lo, T) \
                    if left.capacity >= lo + T else \
                    jnp.pad(c.valid[lo:], (0, T - (left.capacity - lo)))
            ltree[n] = (d, v)
        while True:
            fn = _build_tile_kernel(sig, pred.key(), names_l, names_r,
                                    pred, schema, dicts, T, B, out_cap,
                                    how == "left")
            res = fn(ltree, jnp.asarray(tile_rows), rtree, rcount)
            out, cnt = res[0], res[1]
            n_match = int(jax.device_get(cnt))
            if n_match <= out_cap:
                break
            out_cap = _pow2(n_match)
        if how == "left":
            m = np.asarray(jax.device_get(res[2]))[:tile_rows]
            matched_host.append(m)
        cols: Dict[str, Column] = {}
        for n in names_l:
            src = left.columns[n]
            d, v = out[n]
            cols[n] = Column(d, v, src.dtype, src.dictionary)
        for n in names_r:
            src = right.columns[n]
            d, v = out[n]
            cols[n] = Column(d, v, src.dtype, src.dictionary)
        parts.append(Table(cols, n_match, REP, None))

    if not parts:
        combined = {}
        for n in names_l:
            c = left.columns[n]
            combined[n] = c
        for n in names_r:
            combined[n] = right.columns[n]
        base = Table(combined, 0, REP, None)
        out = base
    elif len(parts) == 1:
        out = parts[0]
    else:
        out = R.concat_tables(parts)

    if how == "left":
        unmatched = ~np.concatenate(matched_host) if matched_host \
            else np.ones(left.nrows, dtype=bool)
        if unmatched.any():
            idx = np.flatnonzero(unmatched)
            pad = _null_padded_left_rows(left, right, idx)
            out = R.concat_tables([out, pad]) if out.nrows else pad
    return R.shrink_to_fit(out) if out.nrows else out


def _null_padded_left_rows(left: Table, right: Table,
                           idx: np.ndarray) -> Table:
    """Unmatched probe rows with all-null build columns (left join)."""
    n = len(idx)
    cap = round_capacity(max(n, 1))
    gi = jnp.asarray(np.pad(idx, (0, cap - n)))
    cols: Dict[str, Column] = {}
    for name, c in left.columns.items():
        d = c.data[gi]
        v = None if c.valid is None else c.valid[gi]
        cols[name] = Column(d, v, c.dtype, c.dictionary)
    for name, c in right.columns.items():
        z = jnp.zeros((cap,), dtype=c.data.dtype)
        cols[name] = Column(z, jnp.zeros((cap,), bool), c.dtype,
                            c.dictionary)
    return Table(cols, n, REP, None)


# ---------------------------------------------------------------------------
# interval fast path
# ---------------------------------------------------------------------------

def match_interval_pattern(pred: Expr, left_cols, right_cols
                           ) -> Optional[Tuple[str, str]]:
    """Detect a point-in-interval conjunct pair: (p >= lo & p <= hi)
    with p from the probe side and lo/hi from the build side (any
    operand order / strictness). Returns (probe_col, build_lo_col) for
    band pruning, or None."""
    conj: List[Expr] = []

    def flat(e):
        if isinstance(e, BinOp) and e.op == "&":
            flat(e.left)
            flat(e.right)
        else:
            conj.append(e)
    flat(pred)
    lower = None  # (p, lo): p >= lo
    upper = None  # (p, hi): p <= hi
    for e in conj:
        if not (isinstance(e, BinOp) and e.op in (">", ">=", "<", "<=")
                and isinstance(e.left, ColRef)
                and isinstance(e.right, ColRef)):
            continue
        a, b, op = e.left.name, e.right.name, e.op
        if op in ("<", "<="):
            a, b = b, a  # normalize to a >= b / a > b
        # now a (>|>=) b
        if a in left_cols and b in right_cols:
            lower = (a, b)
        elif b in left_cols and a in right_cols:
            upper = (b, a)
    if lower and upper and lower[0] == upper[0]:
        return lower[0], lower[1]
    return None


def nl_join_interval(left: Table, right: Table, pred: Expr,
                     probe_col: str, lo_col: str,
                     how: str = "inner") -> Table:
    """Band-pruned nested-loop join: probe sorted by the point column,
    build sorted by interval start; each probe tile only grids against
    build rows whose start <= the tile's max point (a build prefix).
    Full predicate still evaluated on the pruned grid, so correctness
    never depends on the pruning (reference: the sort-based interval
    join, bodo/libs/_interval_join.cpp)."""
    from bodo_tpu import relational as R
    if left.column(probe_col).valid is not None or \
            right.column(lo_col).valid is not None:
        # null sort keys carry sentinel physical values, breaking the
        # monotone-prefix pruning invariant — full grid instead
        return nl_join_rep(left, right, pred, how)
    left_s = R.sort_table(R.shrink_to_fit(left), [probe_col])
    right_s = R.sort_table(R.shrink_to_fit(right), [lo_col])
    # host copy of the sort columns to size each tile's build prefix
    p_host = np.asarray(jax.device_get(left_s.column(probe_col).data)
                        )[:left_s.nrows]
    lo_host = np.asarray(jax.device_get(right_s.column(lo_col).data)
                         )[:right_s.nrows]
    B_full = max(right_s.nrows, 1)
    T = _pow2(max(min(left_s.capacity, max(_GRID_BUDGET // B_full, 1)),
                  1))
    parts: List[Table] = []
    n_tiles = max(1, -(-left_s.nrows // T)) if left_s.nrows else 0
    for ti in range(n_tiles):
        lo_r = ti * T
        tile_rows = min(T, left_s.nrows - lo_r)
        pmax = p_host[lo_r:lo_r + tile_rows].max()
        # build prefix: rows with start <= pmax
        c1 = int(np.searchsorted(lo_host, pmax, side="right"))
        bcap = _pow2(max(c1, 1))
        tile = _slice_rep(left_s, lo_r, T, tile_rows)
        prefix = _slice_rep(right_s, 0, bcap, min(c1, right_s.nrows))
        # per-tile left join is globally correct: tiles partition the
        # probe rows, so each tile null-pads its own unmatched rows
        parts.append(nl_join_rep(tile, prefix, pred, how))
    if not parts:
        return nl_join_rep(left_s, right_s, pred, how)
    out = parts[0] if len(parts) == 1 else R.concat_tables(
        [p for p in parts if p.nrows] or parts[:1])
    return out


def _slice_rep(t: Table, off: int, cap: int, rows: int) -> Table:
    cols: Dict[str, Column] = {}
    for n, c in t.columns.items():
        end = min(off + cap, c.capacity)
        d = c.data[off:end]
        if d.shape[0] < cap:
            d = jnp.pad(d, (0, cap - d.shape[0]))
        v = None
        if c.valid is not None:
            v = c.valid[off:end]
            if v.shape[0] < cap:
                v = jnp.pad(v, (0, cap - v.shape[0]))
        cols[n] = Column(d, v, c.dtype, c.dictionary)
    return Table(cols, rows, REP, None)
