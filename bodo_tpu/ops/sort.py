"""Sort kernels: local multi-key sort and distributed sample sort.

TPU-native replacement for the reference's external-merge sort and
sample-based range partitioning (bodo/libs/_array_operations.cpp
sort_values paths, bodo/libs/streaming/_sort.cpp, sample bounds via
bodo/libs/distributed_api.py:2114 get_chunk_bounds). The comparator-based
C++ sort becomes `lax.sort` over order-preserving uint64 encodings
(ops/sort_encoding.py); the MPI range shuffle becomes splitter-based
destination assignment + fixed-capacity all_to_all (parallel/shuffle.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from bodo_tpu.config import config
from bodo_tpu.ops import kernels as K
from bodo_tpu.ops import sort_encoding as SE
from bodo_tpu.parallel import collectives as C
from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.utils.kernel_cache import bounded_jit, cached_builder

# oversampling factor for splitter selection (samples per shard = OS * S)
_OVERSAMPLE = 8


def _sort_operands(keys: Sequence[Tuple], ascending: Sequence[bool],
                   na_last: bool, padmask):
    ops: List = []
    for (data, valid), asc in zip(keys, ascending):
        ops.extend(SE.key_operands(data, valid, ascending=asc,
                                   na_last=na_last, padmask=padmask))
    return ops


@bounded_jit(static_argnames=("num_keys", "ascending", "na_last"))
def sort_local(arrays, count, num_keys: int, ascending: Tuple[bool, ...],
               na_last: bool = True):
    """Stable multi-key sort of all columns; first `num_keys` arrays are
    the sort keys. Returns (sorted arrays, perm)."""
    cap = arrays[0][0].shape[0]
    padmask = K.row_mask(count, cap)
    ops = _sort_operands(arrays[:num_keys], ascending, na_last, padmask)
    nko = len(ops)
    ops.append(jnp.arange(cap))
    perm = lax.sort(tuple(ops), num_keys=nko, is_stable=True)[-1]
    out = tuple((None if d is None else d[perm],
                 None if v is None else v[perm]) for d, v in arrays)
    return out, perm


def _partition_key(keys: Sequence[Tuple], ascending: Sequence[bool],
                   na_last: bool, padmask):
    """Fold the leading sort key into one uint64 for range partitioning.

    Ties from the fold are harmless: rows with equal partition keys may
    land on adjacent shards, which still yields a globally sorted
    concatenation (every row on shard i sorts <= every row on shard i+1).
    """
    data, valid = keys[0]
    enc = SE.encode_value(data, ascending[0])
    null = SE.null_flag(data, valid)
    # layout: [2 bits rank][62 bits value] — rank orders nulls/padding
    rank = jnp.full(data.shape, np.uint64(1), dtype=jnp.uint64)
    if null is not None:
        rank = jnp.where(null, np.uint64(2) if na_last else np.uint64(0),
                         rank)
    pk = (rank << np.uint64(62)) | (enc >> np.uint64(2))
    return jnp.where(padmask, pk, np.uint64(0xFFFFFFFFFFFFFFFF))


@cached_builder("sort")
def _build_sort_sharded(mesh_key, num_arrays: int, num_keys: int,
                        ascending: Tuple[bool, ...], na_last: bool,
                        bucket_cap: int):
    from bodo_tpu.parallel.shuffle import _MESHES, shuffle_rows
    mesh = _MESHES[mesh_key]
    axis = config.data_axis
    S = mesh.shape[axis]

    def body(arrays, counts):
        count = counts[0]
        cap = arrays[0][0].shape[0]
        padmask = K.row_mask(count, cap)
        pk = _partition_key(arrays[:num_keys], ascending, na_last, padmask)

        # 1. sample partition keys at even local quantiles
        k = _OVERSAMPLE * S
        pk_sorted = lax.sort(pk)
        idx = (jnp.arange(k) * jnp.maximum(count, 1)) // k
        samples = pk_sorted[jnp.clip(idx, 0, cap - 1)]
        samples = jnp.where(jnp.arange(k) * jnp.maximum(count, 1) // k < count,
                            samples, np.uint64(0xFFFFFFFFFFFFFFFF))
        all_samples = C.all_gather_rows(samples, axis)          # [S*k]
        svalid = all_samples != np.uint64(0xFFFFFFFFFFFFFFFF)
        s_sorted = lax.sort(jnp.where(svalid, all_samples,
                                      np.uint64(0xFFFFFFFFFFFFFFFF)))
        nvalid = jnp.sum(svalid)
        # splitters: S-1 even quantiles of the valid samples
        spl_idx = (jnp.arange(1, S) * jnp.maximum(nvalid, 1)) // S
        splitters = s_sorted[jnp.clip(spl_idx, 0, S * k - 1)]

        # 2. range shuffle (dest = #splitters < pk): the Pallas radix
        # partition kernel decides uint64 order by 16-bit planes on the
        # VPU; XLA searchsorted when the gate is closed
        from bodo_tpu.ops import pallas_kernels as PK
        dest = PK.range_partition(pk, splitters)
        if dest is None:
            dest = jnp.searchsorted(splitters, pk,
                                    side="right").astype(jnp.int32)
        flat: List = []
        slots = []
        for d, v in arrays:
            flat.append(d)
            if v is not None:
                slots.append(True)
                flat.append(v)
            else:
                slots.append(False)
        out, cnt2, ovf = shuffle_rows(dest, flat, count, S, bucket_cap, axis)
        rebuilt = []
        j = 0
        for has_valid in slots:
            if has_valid:
                rebuilt.append((out[j], out[j + 1].astype(bool)))
                j += 2
            else:
                rebuilt.append((out[j], None))
                j += 1

        # 3. final local sort
        sorted_arrays, _ = sort_local(tuple(rebuilt), cnt2, num_keys,
                                      ascending, na_last)
        return sorted_arrays, cnt2[None], ovf[None]

    shd = C.smap(body, in_specs=(P(axis), P(axis)),
                 out_specs=(P(axis), P(axis), P(axis)), mesh=mesh)
    return jax.jit(shd)


def sort_sharded(arrays, counts, num_keys: int, ascending: Tuple[bool, ...],
                 na_last: bool = True, mesh=None):
    """Distributed sample sort of row-sharded columns.

    Globally sorted result: shard i's rows all sort <= shard i+1's rows,
    each shard locally sorted. Splitter-balanced buckets are sized
    optimistically (cap/S × skew headroom) and grown on overflow up to the
    always-safe bound of cap per (src,dest) pair.
    Returns (sorted arrays, new counts [S]).
    """
    import numpy as np

    from bodo_tpu.parallel.shuffle import _mesh_key
    from bodo_tpu.table.table import round_capacity
    m = mesh or mesh_mod.get_mesh()
    S = m.shape[config.data_axis]
    cap = arrays[0][0].shape[0] // S
    bucket_cap = min(round_capacity(
        int(config.shuffle_skew_factor * cap / S) + 64), cap)
    while True:
        fn = _build_sort_sharded(_mesh_key(m), len(arrays), num_keys,
                                 tuple(ascending), na_last, bucket_cap)
        out, cnts, ovf = fn(tuple(arrays), counts)
        if not np.asarray(jax.device_get(ovf)).any():
            return out, cnts
        if bucket_cap >= cap:
            raise RuntimeError("sort shuffle overflow at safe capacity")
        bucket_cap = min(bucket_cap * 4, cap)
