"""Shared kernel utilities: padding masks, null handling, compaction.

Replaces the reference's C++ array utilities (bodo/libs/_array_utils.cpp,
_array_build_buffer.cpp) with jit-traceable equivalents. All kernels obey
the padded-capacity convention: arrays are fixed-capacity, the first
`count` rows are real, the rest is padding.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def row_mask(count, capacity: int):
    """Boolean mask of real (non-padding) rows."""
    return jnp.arange(capacity) < count


def value_ok(data, valid, padmask):
    """Mask of rows whose value participates in aggregation:
    real row AND not null (explicit mask or float NaN)."""
    ok = padmask
    if valid is not None:
        ok = ok & valid
    if jnp.issubdtype(data.dtype, jnp.floating):
        ok = ok & ~jnp.isnan(data)
    return ok


def compact(mask, arrays: Tuple, capacity_out: Optional[int] = None):
    """Stable-compact rows where `mask` is True to the front.

    Returns (compacted arrays, new_count). Rows past new_count are zeroed.
    This is the workhorse for filters and shuffle-receive cleanup — the
    analogue of the reference's RetrieveTable/filter paths
    (bodo/libs/_array_utils.cpp).
    """
    cap = mask.shape[0]
    out_cap = capacity_out if capacity_out is not None else cap
    pos = jnp.cumsum(mask) - 1
    idx = jnp.where(mask, pos, out_cap)  # out-of-range rows dropped
    outs = []
    for a in arrays:
        if a is None:
            outs.append(None)
            continue
        z = jnp.zeros((out_cap,) + a.shape[1:], dtype=a.dtype)
        outs.append(z.at[idx].set(a, mode="drop"))
    return tuple(outs), jnp.sum(mask)


def gather_rows(perm, arrays: Tuple):
    """Apply a row permutation/selection index to several arrays."""
    return tuple(None if a is None else a[perm] for a in arrays)


def fill_null(data, valid, fill):
    """Replace null slots with `fill` (for min/max identity values)."""
    if valid is None:
        if jnp.issubdtype(data.dtype, jnp.floating):
            return jnp.where(jnp.isnan(data), fill, data)
        return data
    return jnp.where(valid, data, fill)
