"""Datetime field extraction kernels (int64 ns ticks → civil fields).

TPU-native replacement for the reference's Timestamp/datetime extension
kernels (bodo/hiframes/pd_timestamp_ext.py, series_dt_impl.py). All
kernels are branch-free integer arithmetic over the VPU, using the
standard civil-from-days algorithm; no host callbacks.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

NS_PER_DAY = np.int64(86_400_000_000_000)
NS_PER_HOUR = np.int64(3_600_000_000_000)
NS_PER_MIN = np.int64(60_000_000_000)
NS_PER_SEC = np.int64(1_000_000_000)


def days_from_ns(ns):
    """Days since 1970-01-01 (floor division — correct for pre-epoch)."""
    return jnp.floor_divide(ns, NS_PER_DAY).astype(jnp.int64)


def _civil(days):
    """(year, month, day) from days-since-epoch; branch-free."""
    z = days + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(doe - doe // 1460 + doe // 36524 - doe // 146096,
                           365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int64), m.astype(jnp.int64), d.astype(jnp.int64)


def year(ns):
    return _civil(days_from_ns(ns))[0]


def month(ns):
    return _civil(days_from_ns(ns))[1]


def day(ns):
    return _civil(days_from_ns(ns))[2]


def hour(ns):
    tod = ns - days_from_ns(ns) * NS_PER_DAY
    return jnp.floor_divide(tod, NS_PER_HOUR).astype(jnp.int64)


def minute(ns):
    tod = ns - days_from_ns(ns) * NS_PER_DAY
    return jnp.floor_divide(tod % NS_PER_HOUR, NS_PER_MIN).astype(jnp.int64)


def second(ns):
    tod = ns - days_from_ns(ns) * NS_PER_DAY
    return jnp.floor_divide(tod % NS_PER_MIN, NS_PER_SEC).astype(jnp.int64)


def dayofweek(ns):
    """Monday=0 (pandas convention); 1970-01-01 was a Thursday (=3)."""
    return ((days_from_ns(ns) + 3) % 7).astype(jnp.int64)


def date(ns):
    """Date as int32 days since epoch (the DATE physical repr)."""
    return days_from_ns(ns).astype(jnp.int32)


def dayofyear(ns):
    y, m, d = _civil(days_from_ns(ns))
    # days from civil for Jan 1 of y
    jan1 = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
    return (days_from_ns(ns) - jan1 + 1).astype(jnp.int64)


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def quarter(ns):
    return jnp.floor_divide(month(ns) - 1, 3) + 1


def week(ns):
    """ISO 8601 week number (1-53), branch-free: the ISO week of a date
    is the week containing its Thursday."""
    days = days_from_ns(ns)
    # Thursday of this date's ISO week (Monday=0 convention)
    thu = days - dayofweek(ns) + 3
    y, _, _ = _civil(thu)
    jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return (jnp.floor_divide(thu - jan1, 7) + 1).astype(jnp.int64)


def _month_len(y, m):
    """Days in civil month (y, m)."""
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    base = jnp.asarray(
        np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31],
                 dtype=np.int64))[m - 1]
    return jnp.where((m == 2) & leap, 29, base)


def add_months(ns, n):
    """Calendar month addition with day-of-month clamping (SQL DATEADD
    semantics: Jan 31 + 1 month = Feb 28/29)."""
    y, m, d = _civil(days_from_ns(ns))
    tod = ns - days_from_ns(ns) * NS_PER_DAY
    tot = (y * 12 + (m - 1)) + n
    y2 = jnp.floor_divide(tot, 12)
    m2 = tot - y2 * 12 + 1
    d2 = jnp.minimum(d, _month_len(y2, m2))
    return _days_from_civil(y2, m2, d2) * NS_PER_DAY + tod


def trunc(unit: str, ns):
    """DATE_TRUNC to ns ticks at the start of the unit."""
    if unit in ("second", "minute", "hour", "day"):
        step = {"second": NS_PER_SEC, "minute": NS_PER_MIN,
                "hour": NS_PER_HOUR, "day": NS_PER_DAY}[unit]
        return jnp.floor_divide(ns, step) * step
    if unit == "week":  # ISO week start (Monday)
        days = days_from_ns(ns)
        return (days - dayofweek(ns)) * NS_PER_DAY
    y, m, _ = _civil(days_from_ns(ns))
    one = jnp.ones_like(y)
    if unit == "month":
        return _days_from_civil(y, m, one) * NS_PER_DAY
    if unit == "quarter":
        qm = ((m - 1) // 3) * 3 + 1
        return _days_from_civil(y, qm, one) * NS_PER_DAY
    if unit == "year":
        return _days_from_civil(y, one, one) * NS_PER_DAY
    raise ValueError(f"unknown trunc unit {unit}")


def month_index(ns):
    """Absolute month number (year*12 + month-1) — datediff building block."""
    y, m, _ = _civil(days_from_ns(ns))
    return y * 12 + (m - 1)


FIELDS = {
    "year": year, "month": month, "day": day, "hour": hour,
    "minute": minute, "second": second, "dayofweek": dayofweek,
    "weekday": dayofweek, "dayofyear": dayofyear, "quarter": quarter,
    "date": date, "week": week, "weekofyear": week,
}
