"""Order-preserving uint64 key encodings for multi-key sorts.

The reference sorts with type-dispatched C++ comparators
(bodo/libs/_array_operations.cpp KeyComparisonAsPython). On TPU we instead
map every key column to a uint64 whose unsigned order equals the logical
order (IEEE-754 total-order trick for floats, sign-bit flip for ints,
dictionary codes for strings — dictionaries are kept sorted at ingest so
code order == lexicographic order). Descending keys invert bits.

Nulls and padding rows are NOT folded into the value encoding (clamping
the value range to make room for sentinels collapses distinct extreme
values — e.g. bool False/True, INT64_MIN vs MIN+1). Instead each key
contributes *two* sort operands: a small rank operand (padding/null
ordering) followed by the full-width value encoding; `lax.sort` with
num_keys spanning both gives exact lexicographic order.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

_SIGN64 = np.uint64(0x8000000000000000)


def encode_value(data, ascending: bool = True):
    """uint64 encoding of values; unsigned order == logical order.
    Exact (bijective) — no range clamping."""
    dt = data.dtype
    if jnp.issubdtype(dt, jnp.floating):
        data = data + jnp.zeros((), dt)  # -0.0 -> +0.0 (equal keys, one code)
        if dt == jnp.float32:
            bits = data.view(jnp.uint32).astype(jnp.uint64) << np.uint64(32)
        else:
            bits = data.view(jnp.uint64)
        sign = (bits & _SIGN64) != 0
        enc = jnp.where(sign, ~bits, bits | _SIGN64)
    elif dt == jnp.bool_:
        enc = data.astype(jnp.uint64)
    elif jnp.issubdtype(dt, jnp.unsignedinteger):
        enc = data.astype(jnp.uint64)
    else:  # signed ints (incl. dict codes, datetimes)
        enc = data.astype(jnp.int64).view(jnp.uint64) ^ _SIGN64
    return ~enc if not ascending else enc


def decode_value(enc, dtype):
    """Inverse of encode_value (ascending form): uint64 codes back to
    values of `dtype` — exact for every supported dtype (the encoding is
    bijective)."""
    dt = jnp.dtype(dtype)
    if jnp.issubdtype(dt, jnp.floating):
        sign = (enc & _SIGN64) != 0
        bits = jnp.where(sign, enc ^ _SIGN64, ~enc)
        if dt == jnp.float32:
            return (bits >> np.uint64(32)).astype(jnp.uint32) \
                .view(jnp.float32)
        return bits.view(jnp.float64)
    if dt == jnp.bool_:
        return enc != 0
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        return enc.astype(dt)
    return (enc ^ _SIGN64).view(jnp.int64).astype(dt)


def null_flag(data, valid=None):
    """Boolean null indicator (explicit mask OR float NaN)."""
    null = None
    if valid is not None:
        null = ~valid
    if jnp.issubdtype(data.dtype, jnp.floating):
        isnan = jnp.isnan(data)
        null = isnan if null is None else (null | isnan)
    return null


def key_operands(data, valid=None, ascending: bool = True,
                 na_last: bool = True, padmask=None) -> List:
    """Sort operands for one key column: [rank, value_enc].

    rank (uint8) orders padding rows last, then nulls per na_last, then
    real values; value_enc breaks ties exactly. Pass the resulting lists
    concatenated to lax.sort with num_keys = total operand count.
    """
    enc = encode_value(data, ascending)
    null = null_flag(data, valid)
    if null is None and padmask is None:
        return [enc]
    rank = jnp.zeros(data.shape, dtype=jnp.uint8)
    if null is not None:
        rank = jnp.where(null, np.uint8(2) if na_last else np.uint8(0), np.uint8(1))
    else:
        rank = jnp.full(data.shape, np.uint8(1), dtype=jnp.uint8)
    if padmask is not None:
        rank = jnp.where(padmask, rank, np.uint8(3))  # padding strictly last
    return [rank, enc]
