"""Join kernels: sort-based equi-join with exact multi-key matching.

TPU-native replacement for the reference's hash-join family
(bodo/libs/_hash_join.cpp, _join_hashing.cpp, streaming/_join.h:892
HashJoinState). Hash tables don't map well to XLA's static dataflow, so
we use a union-segmentation design instead (SURVEY.md §7 "sort-based
fallback is the safety net", here promoted to the primary):

  1. concatenate probe+build key columns and segment them with the same
     stable sort machinery as groupby — every row gets an exact group id
     (gid); key equality becomes integer gid equality, which also makes
     multi-key joins exact without composite-key bit-packing.
  2. order build rows by gid; per-gid [start, count) ranges come from a
     cumsum. Each probe row matches `count[gid]` build rows.
  3. expansion: output slot j maps back to its (probe, build) pair with
     one searchsorted over the exclusive cumsum of match counts — fully
     static shapes, with an overflow flag the host uses to re-bucket
     (the analogue of the reference's partition re-splitting).

Dynamic output size is handled by the two-call pattern: `join_count`
returns the exact row count, the host picks a padded capacity bucket,
then `join_local` materializes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bodo_tpu.ops import kernels as K
from bodo_tpu.ops import sort_encoding as SE
from bodo_tpu.utils.kernel_cache import bounded_jit


def _union_gids(probe_keys, build_keys, p_padmask, b_padmask,
                null_equal: bool = False):
    """Segment the union of probe+build keys; returns (gid_p, gid_b).

    Excluded rows get gid == ucap (sentinel, matches nothing because
    build counts are only accumulated for real rows). null_equal=False
    (SQL): null keys are excluded — they never match. null_equal=True
    (pandas merge): nulls form a real group and match other nulls
    (a null in any key position compares equal to a null there)."""
    pcap = probe_keys[0][0].shape[0]
    bcap = build_keys[0][0].shape[0]
    ucap = pcap + bcap
    unionmask = jnp.concatenate([p_padmask, b_padmask])
    operands: List = []
    ukeys = []
    for (pd_, pv), (bd, bv) in zip(probe_keys, build_keys):
        d = jnp.concatenate([pd_, bd.astype(pd_.dtype)])
        if pv is None and bv is None:
            v = None
        else:
            pv_ = pv if pv is not None else jnp.ones(pcap, dtype=bool)
            bv_ = bv if bv is not None else jnp.ones(bcap, dtype=bool)
            v = jnp.concatenate([pv_, bv_])
        ukeys.append((d, v))
        nf = SE.null_flag(d, v)
        if not null_equal:
            if nf is not None:
                unionmask = unionmask & ~nf
            operands.extend(SE.key_operands(d, v, padmask=unionmask))
        elif nf is not None:
            # sort all nulls of this key into one adjacent block with a
            # CONSTANT value encoding (zeroed data) — a mask-null's
            # garbage payload must not scatter equal follow-on keys
            dz = jnp.where(nf, jnp.zeros((), d.dtype), d)
            rank = jnp.where(nf, jnp.uint8(2), jnp.uint8(1))
            rank = jnp.where(unionmask, rank, jnp.uint8(3))
            operands.extend([rank, SE.encode_value(dz)])
        else:
            operands.extend(SE.key_operands(d, v, padmask=unionmask))
    nko = len(operands)
    operands.append(jnp.arange(ucap))
    perm = lax.sort(tuple(operands), num_keys=nko, is_stable=True)[-1]
    umask_s = unionmask[perm]
    pos = jnp.arange(ucap)
    diff = jnp.zeros(ucap, dtype=bool).at[0].set(True)
    for d, v in ukeys:
        ks = d[perm]
        if null_equal:
            # canonicalize: all nulls (mask or NaN) compare equal to each
            # other and different from every value (raw NaN != NaN would
            # make each null row its own group)
            nf = SE.null_flag(d, v)
            if nf is not None:
                ns = nf[perm]
                ks = jnp.where(ns, jnp.zeros((), ks.dtype), ks)
                diff = diff | (ns != jnp.roll(ns, 1))
        diff = diff | (ks != jnp.roll(ks, 1))
    new_group = umask_s & (diff | (pos == 0))
    seg = jnp.maximum(jnp.cumsum(new_group) - 1, 0)
    seg = jnp.where(umask_s, seg, ucap)  # sentinel for excluded rows
    gid = jnp.zeros(ucap, dtype=jnp.int64).at[perm].set(seg)
    return gid[:pcap], gid[pcap:]


def _hash_gids(probe_keys, build_keys, p_pad, b_pad,
               null_equal: bool = False):
    """Hash-table alternative to `_union_gids`: build keys claim slots
    in a scatter-claim table (ops/hashtable.py), gid = dense build-key
    group id; probe rows look their gid up with lock-step probe rounds.

    Duplicate build keys are the NORMAL case (they share a slot, and the
    downstream per-gid [start, count) expansion emits every duplicate) —
    the reference's hash-join behavior (bodo/libs/_hash_join.cpp),
    realized as parallel scatter/gather rounds instead of serial chains.
    Costs O(rounds) scatters over the BUILD side plus one bcap-row sort
    downstream, vs the union sort's O((P+B) log (P+B)) — the win when
    the probe side dwarfs the build side (FK joins).

    Returns (gid_p, gid_b, unresolved); sentinel gid == pcap + bcap for
    excluded/unmatched rows, matching the union convention. `unresolved`
    True → the probe-round cap was hit; caller must use the sort path."""
    from bodo_tpu.ops import hashtable as HT

    pcap = probe_keys[0][0].shape[0]
    bcap = build_keys[0][0].shape[0]
    ucap = pcap + bcap
    pcodes, bcodes, p_ok0, b_ok0 = HT.aligned_codes(probe_keys,
                                                    build_keys, null_equal)
    b_ok = b_pad if b_ok0 is None else (b_pad & b_ok0)
    p_ok = p_pad if p_ok0 is None else (p_pad & p_ok0)
    T = HT.table_size(bcap)
    slot_b, owner, _r, un1 = HT.claim_slots(bcodes, b_ok, T)
    seg_b, _group_row, ng = HT.densify(slot_b, owner, T)
    bidx, un2 = HT.probe_slots(bcodes, owner, pcodes, p_ok, T)
    gid_b = jnp.where(b_ok, seg_b.astype(jnp.int64), ucap)
    gid_p = jnp.where(bidx >= 0,
                      seg_b[jnp.maximum(bidx, 0)].astype(jnp.int64), ucap)
    return gid_p, gid_b, un1 | un2


def _join_plan(probe_keys, build_keys, probe_count, build_count,
               how: str, null_equal: bool = False, method: str = "sort"):
    pcap = probe_keys[0][0].shape[0]
    bcap = build_keys[0][0].shape[0]
    ucap = pcap + bcap
    p_pad = K.row_mask(probe_count, pcap)
    b_pad = K.row_mask(build_count, bcap)
    if method == "hash":
        gid_p, gid_b, unresolved = _hash_gids(probe_keys, build_keys,
                                              p_pad, b_pad, null_equal)
    else:
        gid_p, gid_b = _union_gids(probe_keys, build_keys, p_pad, b_pad,
                                   null_equal)
        unresolved = jnp.zeros((), bool)

    # order build rows by gid (sentinel rows last)
    gid_b_s, b_perm = lax.sort((gid_b, jnp.arange(bcap)), num_keys=1,
                               is_stable=True)
    bc = jax.ops.segment_sum(jnp.ones(bcap, dtype=jnp.int64),
                             jnp.minimum(gid_b, ucap),
                             num_segments=ucap + 1)
    bc = bc.at[ucap].set(0)  # sentinel gid matches nothing
    starts = jnp.cumsum(bc) - bc

    keyed = gid_p < ucap  # real probe rows with non-null keys
    matches = jnp.where(keyed, bc[jnp.minimum(gid_p, ucap)], 0)
    if how in ("left", "outer"):
        L = jnp.where(p_pad, jnp.maximum(matches, 1), 0)
    else:  # inner
        L = matches
    offsets = jnp.cumsum(L) - L
    total = jnp.sum(L)

    # full outer: build rows whose gid no real keyed probe row shares are
    # appended after the probe-driven rows (null-key build rows — gid ==
    # sentinel — never match, so they are unmatched too, SQL semantics)
    unm_idx = None
    n_unm = jnp.zeros((), jnp.int64)
    if how == "outer":
        pc_per_gid = jax.ops.segment_sum(
            jnp.where(p_pad & keyed, 1, 0).astype(jnp.int64),
            jnp.minimum(gid_p, ucap), num_segments=ucap + 1)
        unmatched_b = b_pad & (
            (gid_b >= ucap) | (pc_per_gid[jnp.minimum(gid_b, ucap)] == 0))
        (unm_idx,), n_unm = K.compact(unmatched_b,
                                      (jnp.arange(bcap, dtype=jnp.int64),))
        total = total + n_unm
    return (gid_p, b_perm, bc, starts, offsets, L, total, p_pad,
            unm_idx, n_unm, unresolved)


@bounded_jit(static_argnames=("num_keys", "how", "null_equal", "method"))
def join_count(probe_keys, build_keys, probe_count, build_count,
               num_keys: int, how: str, null_equal: bool = False,
               method: str = "sort"):
    """Exact output row count of the join (cheap pre-pass; the host uses
    it to pick the materialization capacity bucket). Returns
    (total, unresolved) — unresolved only ever True for method='hash'."""
    plan = _join_plan(probe_keys, build_keys, probe_count,
                      build_count, how, null_equal, method)
    return plan[6], plan[10]


@bounded_jit(static_argnames=("num_keys", "how", "out_capacity",
                              "null_equal", "method"))
def join_local(probe_arrays, build_arrays, probe_count, build_count,
               num_keys: int, how: str, out_capacity: int,
               null_equal: bool = False, method: str = "sort"):
    """Materialize the equi-join.

    probe_arrays/build_arrays: tuples of (data, valid); the first
    `num_keys` of each are the join keys (positionally aligned).
    Returns (out_probe, out_build, out_count, overflow, unresolved):
      out_probe — all probe columns gathered per output row,
      out_build — all build columns (valid=False on unmatched left rows),
      overflow — True if out_capacity was too small (host retries bigger),
      unresolved — method='hash' hit its probe-round cap (pathological
      input; host must re-run with method='sort').
    """
    probe_keys = probe_arrays[:num_keys]
    build_keys = build_arrays[:num_keys]
    (gid_p, b_perm, bc, starts, offsets, L, total, p_pad,
     unm_idx, n_unm, unresolved) = _join_plan(
        probe_keys, build_keys, probe_count, build_count, how, null_equal,
        method)
    ucap = gid_p.shape[0] + b_perm.shape[0]
    bcap = b_perm.shape[0]
    total_probe = total - n_unm  # probe-driven rows (== total unless outer)

    j = jnp.arange(out_capacity)
    live = j < total
    probe_row = live & (j < total_probe)
    pidx = jnp.clip(jnp.searchsorted(offsets, j, side="right") - 1,
                    0, gid_p.shape[0] - 1)
    k = j - offsets[pidx]
    g = jnp.minimum(gid_p[pidx], ucap)
    matched = probe_row & (k < bc[g])
    bpos = jnp.clip(starts[g] + k, 0, bcap - 1)
    bidx = b_perm[bpos]
    if how == "outer":
        # appended unmatched-build rows: slots [total_probe, total)
        appended = live & (j >= total_probe)
        k_app = jnp.clip(j - total_probe, 0, bcap - 1)
        bidx = jnp.where(appended, unm_idx[k_app], bidx)
        build_emit = matched | appended
    else:
        build_emit = matched

    out_probe = []
    for d, v in probe_arrays:
        od = jnp.where(probe_row, d[pidx], jnp.zeros((), d.dtype))
        base_v = probe_row if v is None else (probe_row & v[pidx])
        # probe columns are nullable on appended build-only rows
        ov = base_v if how == "outer" else (
            None if v is None else base_v)
        out_probe.append((od, ov))
    out_build = []
    for d, v in build_arrays:
        od = jnp.where(build_emit, d[bidx], jnp.zeros((), d.dtype))
        base_v = build_emit if v is None else (build_emit & v[bidx])
        # build side columns are nullable after a left/outer join
        ov = base_v if how in ("left", "outer") else (
            None if v is None else base_v)
        out_build.append((od, ov))
    out_count = jnp.minimum(total, out_capacity)
    overflow = total > out_capacity
    return (tuple(out_probe), tuple(out_build), out_count, overflow,
            unresolved)


@bounded_jit(static_argnames=("out_capacity",))
def cross_local(probe_arrays, build_arrays, probe_count, build_count,
                out_capacity: int):
    """Cartesian product in pandas row order (probe-major: each probe row
    paired with every build row in order). The host computes the exact
    output size (nl * nr) up front, so there is no overflow retry —
    reference analogue: bodo/libs/_nested_loop_join_impl.cpp's block
    product, here a static index transform instead of a loop."""
    pcap = probe_arrays[0][0].shape[0]
    bcap = build_arrays[0][0].shape[0]
    total = probe_count * build_count
    nb = jnp.maximum(build_count, 1)
    j = jnp.arange(out_capacity)
    live = j < total
    pidx = jnp.clip(j // nb, 0, pcap - 1)
    bidx = jnp.clip(j % nb, 0, bcap - 1)

    def _gather(arrays, idx):
        out = []
        for d, v in arrays:
            od = jnp.where(live, d[idx], jnp.zeros((), d.dtype))
            ov = None if v is None else (live & v[idx])
            out.append((od, ov))
        return tuple(out)

    return (_gather(probe_arrays, pidx), _gather(build_arrays, bidx),
            jnp.minimum(total, out_capacity))
