"""Hashing kernels for shuffle partitioning and hash keys.

Replaces the reference's xxh3/murmur3 C++ hashing
(bodo/libs/_array_hash.cpp, vendored murmurhash3/xxhash) with a
vectorized splitmix64-style finalizer that XLA maps onto the VPU.
Collision-safety note: hashes are used only for *partitioning* (dest
shard) and never for key equality — grouping/joins compare real key
values — so 64-bit mixing quality is all we need.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)
_SEED_MIX = np.uint64(0x9E3779B97F4A7C15)


def splitmix64(x):
    """splitmix64 finalizer on uint64 lanes."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> np.uint64(30))) * _C1
    x = (x ^ (x >> np.uint64(27))) * _C2
    return x ^ (x >> np.uint64(31))


def _to_u64(data):
    dt = data.dtype
    if jnp.issubdtype(dt, jnp.floating):
        # canonicalize so equal keys hash equal: -0.0 -> +0.0, all NaN
        # payloads -> one quiet NaN
        data = data + jnp.zeros((), dt)
        data = jnp.where(jnp.isnan(data), jnp.array(np.nan, dt), data)
        if dt == jnp.float64:
            return data.view(jnp.uint64)
        return data.view(jnp.uint32).astype(jnp.uint64)
    if dt == jnp.bool_:
        return data.astype(jnp.uint64)
    return data.astype(jnp.int64).view(jnp.uint64)


def hash_column(data, valid=None):
    """64-bit hash of one column; nulls hash to a fixed tag. A float NaN
    and a mask-null are the same logical null (sort_encoding.null_flag),
    so both take the tag — otherwise the two null forms would land on
    different shards and nulls-match joins would mis-co-locate."""
    h = splitmix64(_to_u64(data))
    null = None
    if valid is not None:
        null = ~valid
    if jnp.issubdtype(data.dtype, jnp.floating):
        isnan = jnp.isnan(data)
        null = isnan if null is None else (null | isnan)
    if null is not None:
        h = jnp.where(null, np.uint64(0xDEAD_BEEF_CAFE_F00D), h)
    return h


def hash_columns(cols: Sequence[Tuple], seed: int = 0):
    """Combined hash over multiple (data, valid) key columns — the
    partition hash of the reference's shuffle (bodo/libs/_shuffle.h:9
    `hash_to_bucket`)."""
    acc = jnp.full(cols[0][0].shape, np.uint64(seed) + _SEED_MIX,
                   dtype=jnp.uint64)
    for data, valid in cols:
        h = hash_column(data, valid)
        acc = splitmix64(acc ^ (h + _SEED_MIX + (acc << np.uint64(6))
                                + (acc >> np.uint64(2))))
    return acc


def dest_shard(hashes, num_shards: int):
    """Destination shard for each row (hash_to_bucket analogue)."""
    return (hashes % np.uint64(num_shards)).astype(jnp.int32)
