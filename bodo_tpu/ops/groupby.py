"""Groupby aggregation kernels.

TPU-native replacement for the reference's hash-groupby C++ family
(bodo/libs/groupby/_groupby*.cpp, streaming/_groupby.cpp). Instead of
hash tables we use the XLA-friendly sort+segment-reduce recipe
(SURVEY.md §7): stable multi-key sort on encoded keys, segment ids from
group boundaries, `jax.ops.segment_*` reductions onto the MXU/VPU.

Aggregations are split into decomposable partial ops + combine + finalize
(the reference's decomposition strategy for its distributed combine step,
bodo/libs/groupby/_groupby_update.cpp), which powers the two-phase
distributed groupby: local pre-aggregation → hash-partition all_to_all
shuffle → combine (parallel/shuffle.py).

var/std use the numerically stable (count, sum, m2) moments with
m2 = Σ(x − mean)² accumulated in float64 (two-pass locally; the
cross-shard term is recovered from per-shard sums at combine — the same
stable var_combine the reference implements,
bodo/libs/groupby/_groupby_update.cpp), never the catastrophically
cancelling E[x²] − E[x]² form.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bodo_tpu.ops import kernels as K
from bodo_tpu.ops import sort_encoding as SE
from bodo_tpu.utils.kernel_cache import bounded_jit

# ---------------------------------------------------------------------------
# agg spec plumbing
# ---------------------------------------------------------------------------

# final op -> (partial ops, combine ops on partial cols)
# var/std partials: float64 (count, sum, m2); the combine for m2 is the
# composite "chan_m2" (exact delta-form Chan combine) which reads the two
# preceding columns (count, sum) — the triple MUST stay in this order.
_VAR_PARTS = ["count", "sum64", "m2"]
# skew/kurt partials extend the stable-moments triple with the centered
# third/fourth moments; their combines are the exact delta-form Chan
# transforms (see chan_m3/chan_m4 in _groupby_local_impl) which read the
# preceding columns — the order here is load-bearing.
_SKEW_PARTS = ["count", "sum64", "m2", "m3"]
_KURT_PARTS = ["count", "sum64", "m2", "m3", "m4"]
DECOMPOSE: Dict[str, List[str]] = {
    "sum": ["sum"],
    "sumnull": ["sumnull"],
    "prod": ["prod"],
    "count": ["count"],
    "size": ["size"],
    "min": ["min"],
    "max": ["max"],
    "first": ["first"],
    "last": ["last"],
    "mean": ["sum", "count"],
    "var": _VAR_PARTS,
    "std": _VAR_PARTS,
    "var0": _VAR_PARTS,
    "std0": _VAR_PARTS,
    "skew": _SKEW_PARTS,
    "kurt": _KURT_PARTS,
}
COMBINE_OF = {"sum": "sum", "sumnull": "sumnull", "sum64": "sum",
              "m2": "chan_m2", "m3": "chan_m3", "m4": "chan_m4",
              "count": "sum", "size": "sum",
              "min": "min", "max": "max", "first": "first", "last": "last",
              "prod": "prod"}


def agg_dtype(op: str, src) -> "object":
    """Logical result DType of an aggregation (decimal-aware): the single
    source of truth shared by plan schema inference and the executors."""
    from bodo_tpu.table import dtypes as dt
    if op in ("count", "size", "nunique"):
        return dt.INT64
    if op.startswith(("listagg", "listaggd")):
        return dt.STRING
    if op in ("min", "max", "first", "last", "mode"):
        return src
    if dt.is_decimal(src):
        if op == "prod":
            raise NotImplementedError(
                "prod over a decimal column: the product of n values "
                "carries scale n·s, which a fixed-scale column can't hold")
        if op in ("sum", "sumnull"):
            # sums overflow the source precision; widen to the full 18
            # digits an int64 holds (scale preserved, values exact)
            return dt.decimal(src.scale)
        return dt.FLOAT64  # mean/var/std/quantiles descale to float
    return dt.from_numpy(result_dtype(op, src.numpy))


def agg_descale_factor(op: str, src) -> float:
    """Factor dividing a physical agg output of a decimal column to get
    the logical float value (1.0 when no descale applies)."""
    from bodo_tpu.table import dtypes as dt
    if not dt.is_decimal(src):
        return 1.0
    if op in ("sum", "sumnull", "prod", "min", "max", "first", "last",
              "count", "size", "nunique", "skew", "kurt", "mode"):
        # skew/kurt are standardized (scale cancels); mode keeps the dtype
        return 1.0
    if op in ("var", "var0"):
        return 10.0 ** (2 * src.scale)
    return 10.0 ** src.scale  # mean/std/median/quantiles


def result_dtype(op: str, dtype):
    d = jnp.dtype(dtype)
    if op in ("count", "size", "nunique"):
        return jnp.dtype(jnp.int64)
    if op in ("sum64", "m2", "m3", "m4", "skew", "kurt"):
        return jnp.dtype(jnp.float64)  # stable moments always accumulate f64
    if op in ("mean", "var", "std", "var0", "std0", "median") or \
            op.startswith(("quantile_", "q:")):
        return jnp.dtype(jnp.float32) if d == jnp.float32 else jnp.dtype(jnp.float64)
    if op in ("sum", "sumnull", "prod"):
        if jnp.issubdtype(d, jnp.floating):
            return d
        if jnp.issubdtype(d, jnp.unsignedinteger):
            return jnp.dtype(jnp.uint64)
        return jnp.dtype(jnp.int64)
    return d  # min/max/first/last


# ---------------------------------------------------------------------------
# core local kernel
# ---------------------------------------------------------------------------

def _group_segments(keys: Sequence[Tuple], count, row_valid=None):
    """Sort rows by keys; return (perm, seg_ids, new_group, padmask_s,
    n_groups). Null-keyed rows are excluded (pandas dropna=True).

    row_valid (optional bool[cap]) marks live rows directly instead of the
    first-`count`-rows convention — used by the streaming merge where live
    rows sit in two packed blocks (state ∪ batch partials)."""
    cap = keys[0][0].shape[0]
    padmask = K.row_mask(count, cap) if row_valid is None else row_valid
    for data, valid in keys:
        if valid is not None:
            padmask = padmask & valid
        if jnp.issubdtype(data.dtype, jnp.floating):
            padmask = padmask & ~jnp.isnan(data)

    operands: list = []
    for d, v in keys:
        operands.extend(SE.key_operands(d, v, padmask=padmask))
    num_key_ops = len(operands)
    operands.append(jnp.arange(cap))
    sorted_ops = lax.sort(tuple(operands), num_keys=num_key_ops,
                          is_stable=True)
    perm = sorted_ops[-1]
    padmask_s = padmask[perm]

    pos = jnp.arange(cap)
    diff = jnp.zeros(cap, dtype=bool).at[0].set(True)
    for data, _ in keys:
        ks = data[perm]
        diff = diff | (ks != jnp.roll(ks, 1))
    new_group = padmask_s & (diff | (pos == 0))
    seg = jnp.maximum(jnp.cumsum(new_group) - 1, 0)
    n_groups = jnp.sum(new_group)
    return perm, seg, new_group, padmask_s, n_groups


def _segment_agg(op: str, v_s, valid_s, seg, padmask_s, out_cap: int):
    """One primitive aggregation over sorted values. Returns (data, valid)."""
    ok = K.value_ok(v_s, valid_s, padmask_s)
    cnt = jax.ops.segment_sum(ok.astype(jnp.int64), seg, num_segments=out_cap)
    rdt = result_dtype(op, v_s.dtype)

    if op == "count":
        return cnt, None
    if op == "size":
        sz = jax.ops.segment_sum(padmask_s.astype(jnp.int64), seg,
                                 num_segments=out_cap)
        return sz, None
    if op in ("sum", "sumnull", "sum64"):
        v = v_s.astype(rdt)
        s = jax.ops.segment_sum(jnp.where(ok, v, 0), seg, num_segments=out_cap)
        if op == "sumnull":  # SQL: SUM over all-null group is NULL
            return s, cnt > 0
        return s, None  # pandas: sum over all-null = 0
    if op == "m2":
        # stable centered second moment Σ(x − mean)², always float64
        v = v_s.astype(jnp.float64)
        s = jax.ops.segment_sum(jnp.where(ok, v, 0.0), seg,
                                num_segments=out_cap)
        mean = s / jnp.maximum(cnt, 1).astype(jnp.float64)
        d = jnp.where(ok, v - mean[seg], 0.0)
        return jax.ops.segment_sum(d * d, seg, num_segments=out_cap), None
    if op == "prod":
        v = v_s.astype(rdt)
        p = jax.ops.segment_prod(jnp.where(ok, v, 1), seg, num_segments=out_cap)
        return p, None
    if op in ("min", "max"):
        if jnp.issubdtype(v_s.dtype, jnp.floating):
            ident = jnp.array(np.inf if op == "min" else -np.inf, v_s.dtype)
        elif v_s.dtype == jnp.bool_:
            ident = jnp.array(op == "min", jnp.bool_)
        else:
            info = jnp.iinfo(v_s.dtype)
            ident = jnp.array(info.max if op == "min" else info.min, v_s.dtype)
        v = jnp.where(ok, v_s, ident)
        f = jax.ops.segment_min if op == "min" else jax.ops.segment_max
        out = f(v, seg, num_segments=out_cap)
        return out, cnt > 0
    if op in ("first", "last"):
        cap = v_s.shape[0]
        if op == "first":
            idx_enc = jnp.where(ok, jnp.arange(cap), cap)
            idx = jax.ops.segment_min(idx_enc, seg, num_segments=out_cap)
        else:
            idx_enc = jnp.where(ok, jnp.arange(cap), -1)
            idx = jax.ops.segment_max(idx_enc, seg, num_segments=out_cap)
        has = (idx >= 0) & (idx < cap)
        out = v_s[jnp.clip(idx, 0, cap - 1)]
        out = jnp.where(has, out, 0)
        return out, has
    if op == "mean":
        v = v_s.astype(rdt)
        s = jax.ops.segment_sum(jnp.where(ok, v, 0), seg, num_segments=out_cap)
        m = s / jnp.maximum(cnt, 1)
        return jnp.where(cnt > 0, m, jnp.nan), None
    if op in ("var", "std", "var0", "std0"):
        # two-pass: mean, then Σ(x − mean)², accumulated in float64
        v = v_s.astype(jnp.float64)
        s = jax.ops.segment_sum(jnp.where(ok, v, 0.0), seg,
                                num_segments=out_cap)
        mean = s / jnp.maximum(cnt, 1).astype(jnp.float64)
        d = jnp.where(ok, v - mean[seg], 0.0)
        m2 = jax.ops.segment_sum(d * d, seg, num_segments=out_cap)
        out = _var_from_m2(m2, cnt, ddof=0 if op.endswith("0") else 1)
        if op.startswith("std"):
            out = jnp.sqrt(out)
        return out.astype(rdt), None
    if op in ("m3", "m4", "skew", "kurt"):
        # centered higher moments, two-pass like m2 (reference:
        # bodo/libs/groupby/ skew/kurt ftypes)
        v = v_s.astype(jnp.float64)
        s = jax.ops.segment_sum(jnp.where(ok, v, 0.0), seg,
                                num_segments=out_cap)
        mean = s / jnp.maximum(cnt, 1).astype(jnp.float64)
        d = jnp.where(ok, v - mean[seg], 0.0)
        m2 = jax.ops.segment_sum(d * d, seg, num_segments=out_cap)
        m3 = jax.ops.segment_sum(d * d * d, seg, num_segments=out_cap)
        if op == "m3":
            return m3, None
        if op == "skew":
            return _skew_from_moments(cnt, m2, m3), None
        m4 = jax.ops.segment_sum(d * d * d * d, seg,
                                 num_segments=out_cap)
        if op == "m4":
            return m4, None
        return _kurt_from_moments(cnt, m2, m4), None
    if op == "nunique":
        raise NotImplementedError("nunique handled in groupby_local")
    raise ValueError(f"unknown agg op: {op}")


def _var_from_m2(m2, cnt, ddof: int = 1):
    """Variance from the centered second moment M2 = Σ(x − mean)²."""
    cntf = cnt.astype(m2.dtype)
    var = m2 / jnp.maximum(cntf - ddof, 1)
    return jnp.where(cnt > ddof, jnp.maximum(var, 0), jnp.nan)


def _skew_from_moments(cnt, m2, m3):
    """pandas-adjusted (Fisher-Pearson) skew from centered moments:
    g1·sqrt(n(n−1))/(n−2) with g1 = (M3/n)/(M2/n)^1.5. Matches pandas
    nanskew: NaN for n<3; 0.0 for zero-variance (constant) groups."""
    n = cnt.astype(jnp.float64)
    safe_m2 = jnp.maximum(m2, 1e-300)
    g1 = (m3 / jnp.maximum(n, 1)) / (safe_m2 / jnp.maximum(n, 1)) ** 1.5
    adj = jnp.sqrt(n * (n - 1)) / jnp.maximum(n - 2, 1)
    out = g1 * adj
    # pandas nanskew: constant groups (m2 == 0) are 0, not NaN
    out = jnp.where(m2 > 0, out, 0.0)
    return jnp.where(cnt >= 3, out, jnp.nan)


def _kurt_from_moments(cnt, m2, m4):
    """pandas-adjusted (Fisher, excess) kurtosis from centered moments:
    [n(n+1)(n−1)·M4/((n−2)(n−3)·M2²)] − 3(n−1)²/((n−2)(n−3)); NaN for
    n<4 or zero variance."""
    n = cnt.astype(jnp.float64)
    safe_m2 = jnp.maximum(m2, 1e-300)
    den = jnp.maximum((n - 2) * (n - 3), 1)
    out = n * (n + 1) * (n - 1) * m4 / (den * safe_m2 * safe_m2) \
        - 3.0 * (n - 1) * (n - 1) / den
    # pandas nankurt: constant groups (m2 == 0) are 0, not NaN
    out = jnp.where(m2 > 0, out, 0.0)
    return jnp.where(cnt >= 4, out, jnp.nan)


def _groupby_local_impl(arrays, count, specs: Tuple[str, ...],
                        out_capacity: int, num_keys: int, row_valid=None):
    keys = arrays[:num_keys]
    values = arrays[num_keys:]
    perm, seg, new_group, padmask_s, n_groups = _group_segments(
        keys, count, row_valid)

    out_keys = []
    idx_scatter = jnp.where(new_group, seg, out_capacity)
    for data, valid in keys:
        k_s = data[perm]
        z = jnp.zeros((out_capacity,), dtype=data.dtype)
        out_keys.append((z.at[idx_scatter].set(k_s, mode="drop"), None))

    out_vals = []
    for i, ((data, valid), op) in enumerate(zip(values, specs)):
        v_s = data[perm]
        valid_s = valid[perm] if valid is not None else None
        if op == "nunique":
            out_vals.append(_nunique(keys, (data, valid), perm, seg,
                                     padmask_s, out_capacity))
        elif op == "mode":
            out_vals.append(_mode((data, valid), perm, seg, padmask_s,
                                  out_capacity))
        elif op.startswith("q:"):  # quantile/median: "q:<float>"
            out_vals.append(_quantile_seg((data, valid), perm, seg,
                                          padmask_s, out_capacity,
                                          float(op[2:])))
        elif op == "chan_m2":
            # composite combine of per-shard (n, sum, m2) partial rows:
            # M2 = Σm2ᵢ + Σnᵢ·(meanᵢ − mean)² — the exact delta-form Chan
            # combine (reference bodo/libs/groupby/_groupby_update.cpp
            # var_combine). Reads the two preceding value columns, which
            # _VAR_PARTS pins to (count, sum64).
            n_s = values[i - 2][0][perm].astype(jnp.float64)
            s_s = values[i - 1][0][perm].astype(jnp.float64)
            m2_s = v_s.astype(jnp.float64)
            okr = K.value_ok(m2_s, valid_s, padmask_s)
            n_tot = jax.ops.segment_sum(jnp.where(okr, n_s, 0.0), seg,
                                        num_segments=out_capacity)
            s_tot = jax.ops.segment_sum(jnp.where(okr, s_s, 0.0), seg,
                                        num_segments=out_capacity)
            mean = s_tot / jnp.maximum(n_tot, 1.0)
            delta = s_s / jnp.maximum(n_s, 1.0) - mean[seg]
            cross = jax.ops.segment_sum(
                jnp.where(okr, n_s * delta * delta, 0.0), seg,
                num_segments=out_capacity)
            m2 = jax.ops.segment_sum(jnp.where(okr, m2_s, 0.0), seg,
                                     num_segments=out_capacity)
            out_vals.append((m2 + cross, None))
        elif op in ("chan_m3", "chan_m4"):
            # exact delta-form combine of centered higher moments: with
            # d_i = mean_i − mean,
            #   M3 = Σ m3_i + 3 d_i m2_i + n_i d_i³
            #   M4 = Σ m4_i + 4 d_i m3_i + 6 d_i² m2_i + n_i d_i⁴
            # reads the preceding partial columns pinned by
            # _SKEW_PARTS/_KURT_PARTS order (count, sum64, m2[, m3]).
            back = 3 if op == "chan_m3" else 4
            n_s = values[i - back][0][perm].astype(jnp.float64)
            s_s = values[i - back + 1][0][perm].astype(jnp.float64)
            m2_s = values[i - back + 2][0][perm].astype(jnp.float64)
            m3_s = (values[i - 1][0][perm].astype(jnp.float64)
                    if op == "chan_m4" else v_s.astype(jnp.float64))
            mk_s = v_s.astype(jnp.float64)
            okr = K.value_ok(mk_s, valid_s, padmask_s)
            n_tot = jax.ops.segment_sum(jnp.where(okr, n_s, 0.0), seg,
                                        num_segments=out_capacity)
            s_tot = jax.ops.segment_sum(jnp.where(okr, s_s, 0.0), seg,
                                        num_segments=out_capacity)
            mean = s_tot / jnp.maximum(n_tot, 1.0)
            d = s_s / jnp.maximum(n_s, 1.0) - mean[seg]
            if op == "chan_m3":
                term = mk_s + 3.0 * d * m2_s + n_s * d * d * d
            else:
                term = mk_s + 4.0 * d * m3_s + 6.0 * d * d * m2_s \
                    + n_s * d * d * d * d
            out_vals.append((jax.ops.segment_sum(
                jnp.where(okr, term, 0.0), seg,
                num_segments=out_capacity), None))
        else:
            out_vals.append(_segment_agg(op, v_s, valid_s, seg, padmask_s,
                                         out_capacity))
    return tuple(out_keys), tuple(out_vals), n_groups


@bounded_jit(static_argnames=("specs", "out_capacity", "num_keys"))
def groupby_local(arrays, count, specs: Tuple[str, ...], out_capacity: int,
                  num_keys: int):
    """Local (single-shard) groupby.

    arrays: tuple of (data, valid) — first `num_keys` are key columns, the
    rest align 1:1 with `specs` (one value column per agg op; repeat the
    column for multiple aggs on it).
    Returns (out_keys, out_vals, n_groups); outputs sorted by key ascending
    (pandas groupby sort=True), packed at the front of the capacity.
    """
    return _groupby_local_impl(arrays, count, specs, out_capacity, num_keys)


@bounded_jit(static_argnames=("specs", "out_capacity", "num_keys"))
def groupby_merge(state_arrays, batch_arrays, n_state, n_batch,
                  specs: Tuple[str, ...], out_capacity: int, num_keys: int):
    """Merge two packed partial-aggregate blocks (streaming accumulate).

    Both inputs are groupby outputs (live rows packed at the front):
    `state_arrays` holds the running partial state (n_state groups),
    `batch_arrays` the latest batch's partials (n_batch groups). Columns
    are concatenated and re-grouped under `specs` (the combine ops), so
    the result is again a packed partial block. This is the streaming
    groupby's accumulate step (reference analogue: the streaming groupby
    build state update, bodo/libs/streaming/_groupby.cpp
    GroupbyState::UpdateGroupsAndCombine)."""
    state_cap = state_arrays[0][0].shape[0]
    batch_cap = batch_arrays[0][0].shape[0]
    mask = jnp.concatenate([jnp.arange(state_cap) < n_state,
                            jnp.arange(batch_cap) < n_batch])

    def cat(sv, bv):
        s_d, s_v = sv
        b_d, b_v = bv
        d = jnp.concatenate([s_d, b_d.astype(s_d.dtype)])
        if s_v is None and b_v is None:
            v = None
        else:
            ones_s = jnp.ones(state_cap, bool) if s_v is None else s_v
            ones_b = jnp.ones(batch_cap, bool) if b_v is None else b_v
            v = jnp.concatenate([ones_s, ones_b])
        return (d, v)

    merged = tuple(cat(s, b) for s, b in zip(state_arrays, batch_arrays))
    return _groupby_local_impl(merged, None, specs, out_capacity, num_keys,
                               row_valid=mask)


def _quantile_seg(value, perm, seg, padmask_s, out_cap: int, q: float):
    """Per-group linear-interpolated quantile (pandas interpolation=
    'linear'; reference analogue bodo/libs/_quantile_alg.cpp): re-sort by
    (group, value) with the raw value as payload, then pick/interpolate
    at (cnt−1)·q per segment."""
    data, valid = value
    cap = data.shape[0]
    v_s = data[perm]
    valid_s = valid[perm] if valid is not None else None
    ok = K.value_ok(v_s, valid_s, padmask_s)
    enc_v = SE.encode_value(v_s)
    seg_key = jnp.where(ok, seg, cap).astype(jnp.int64)
    s_seg, _, s_val = lax.sort(
        (seg_key.view(jnp.uint64), enc_v, v_s.astype(jnp.float64)),
        num_keys=2, is_stable=False)
    pos = jnp.arange(cap)
    okrow = s_seg < jnp.uint64(cap)
    seg_i = jnp.minimum(s_seg, jnp.uint64(out_cap)).astype(jnp.int64)
    start = jax.ops.segment_min(jnp.where(okrow, pos, cap), seg_i,
                                num_segments=out_cap + 1)[:out_cap]
    cnt = jax.ops.segment_sum(okrow.astype(jnp.int64), seg_i,
                              num_segments=out_cap + 1)[:out_cap]
    qpos = (cnt - 1).astype(jnp.float64) * q
    lo = jnp.floor(qpos).astype(jnp.int64)
    hi = jnp.ceil(qpos).astype(jnp.int64)
    frac = qpos - lo.astype(jnp.float64)
    v_lo = s_val[jnp.clip(start + lo, 0, cap - 1)]
    v_hi = s_val[jnp.clip(start + hi, 0, cap - 1)]
    out = v_lo + (v_hi - v_lo) * frac
    return jnp.where(cnt > 0, out, jnp.nan), None


def _mode(value, perm, seg, padmask_s, out_cap: int):
    """Per-group mode (most frequent value; smallest on ties — the
    reference's deterministic mode, bodo/libs/groupby/ mode ftype):
    re-sort by (group, value), run-length the equal-value runs, then a
    two-stage argmax (max run length per group, then min value among
    max-length runs)."""
    data, valid = value
    cap = data.shape[0]
    v_s = data[perm]
    valid_s = valid[perm] if valid is not None else None
    ok = K.value_ok(v_s, valid_s, padmask_s)
    enc_v = SE.encode_value(v_s)
    seg_key = jnp.where(ok, seg, cap).astype(jnp.int64)
    s_seg, s_enc = lax.sort((seg_key.view(jnp.uint64), enc_v),
                            num_keys=2, is_stable=False)
    pos = jnp.arange(cap)
    okrow = s_seg < jnp.uint64(cap)
    newrun = (s_seg != jnp.roll(s_seg, 1)) | (s_enc != jnp.roll(s_enc, 1)) \
        | (pos == 0)
    run_id = jnp.cumsum(newrun) - 1
    run_len = jax.ops.segment_sum(okrow.astype(jnp.int64), run_id,
                                  num_segments=cap)
    this_len = run_len[run_id]
    seg_i = jnp.where(okrow, jnp.minimum(s_seg, jnp.uint64(out_cap))
                      .astype(jnp.int64), out_cap)
    best_len = jax.ops.segment_max(jnp.where(okrow, this_len, 0), seg_i,
                                   num_segments=out_cap + 1)[:out_cap]
    is_best = okrow & (this_len == best_len[jnp.clip(seg_i, 0, out_cap - 1)])
    big = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    best_enc = jax.ops.segment_min(jnp.where(is_best, s_enc, big), seg_i,
                                   num_segments=out_cap + 1)[:out_cap]
    cnt = jax.ops.segment_sum(okrow.astype(jnp.int64), seg_i,
                              num_segments=out_cap + 1)[:out_cap]
    has = cnt > 0
    # exact inverse of the order-preserving encoding — no f64 round-trip
    out = jnp.where(has, SE.decode_value(best_enc, data.dtype),
                    jnp.zeros((), data.dtype))
    return out, has


def _nunique(keys, value, perm, seg, padmask_s, out_cap: int):
    """nunique per group: re-sort by (group seg, value), count distinct
    adjacent values (reference analogue: groupby nunique path in
    bodo/libs/groupby/_groupby_ftypes.cpp)."""
    data, valid = value
    cap = data.shape[0]
    v_s = data[perm]
    valid_s = valid[perm] if valid is not None else None
    ok = K.value_ok(v_s, valid_s, padmask_s)
    # non-ok rows (nulls/padding) get seg_key = cap and sort last; among ok
    # rows the exact value encoding detects distinct adjacent values
    enc_v = SE.encode_value(v_s)
    seg_key = jnp.where(ok, seg, cap).astype(jnp.int64)
    s_seg, s_val = lax.sort((seg_key.view(jnp.uint64), enc_v), num_keys=2,
                            is_stable=False)
    pos = jnp.arange(cap)
    newv = (s_seg != jnp.roll(s_seg, 1)) | (s_val != jnp.roll(s_val, 1)) | (pos == 0)
    okrow = s_seg < jnp.uint64(cap)
    contrib = (newv & okrow).astype(jnp.int64)
    out = jax.ops.segment_sum(contrib,
                              jnp.minimum(s_seg, jnp.uint64(out_cap)).astype(jnp.int64),
                              num_segments=out_cap + 1)[:out_cap]
    return out, None


# ---------------------------------------------------------------------------
# hash-based local kernel (arbitrary key cardinality, no row sort)
# ---------------------------------------------------------------------------

# ops the hash path supports: everything _segment_agg computes from
# (seg, values) alone. Order-sensitive composites (nunique/mode/q:*) and
# the chan_* distributed combines stay on the sort path.
HASH_OPS = frozenset({
    "count", "size", "sum", "sumnull", "sum64", "prod", "min", "max",
    "first", "last", "mean", "var", "std", "var0", "std0",
    "m2", "m3", "m4", "skew", "kurt",
})


@bounded_jit
def _hashed_claim(key_arrays, count):
    """Claim dense group ids for arbitrary keys (no row sort)."""
    from bodo_tpu.ops import hashtable as HT

    cap = key_arrays[0][0].shape[0]
    padmask = K.row_mask(count, cap)
    codes, null_ok = HT.encode_columns(key_arrays, null_equal=False)
    ok = padmask if null_ok is None else (padmask & null_ok)
    T = HT.table_size(cap)
    slot, owner, _r, unresolved = HT.claim_slots(codes, ok, T)
    seg, group_row, n_groups = HT.densify(slot, owner, T)
    return seg, group_row, ok, n_groups, unresolved


@bounded_jit(static_argnames=("specs", "num_keys", "ng_cap"))
def _hashed_agg(arrays, seg, group_row, ok, specs: Tuple[str, ...],
                num_keys: int, ng_cap: int):
    """Aggregate into the ng_cap-sized group space (hash order).

    The segment space is the (host-synced, rounded) GROUP count, not the
    row capacity — on TPU with few groups this is where the Pallas MXU
    one-hot accumulate takes over from scatter-adds."""
    from bodo_tpu.ops import pallas_kernels as PK

    keys = arrays[:num_keys]
    values = arrays[num_keys:]
    cap = keys[0][0].shape[0]
    seg = jnp.where(seg < ng_cap, seg, ng_cap)

    grs = jnp.minimum(jnp.maximum(group_row, 0), cap - 1)
    gvalid = (group_row >= 0)[:ng_cap]
    gkeys = tuple(data[grs][:ng_cap] for data, valid in keys)

    # MXU route: f32 sums/counts/means via one fused one-hot matmul
    mxu = ((PK.use_pallas() or PK.FORCE_INTERPRET)
           and ng_cap <= PK.MAX_MATMUL_SLOTS and cap <= (1 << 24)
           and all(op in ("sum", "count", "size", "mean")
                   for op in specs)
           and all(op in ("count", "size") or
                   (jnp.issubdtype(d.dtype, jnp.floating)
                    and d.dtype.itemsize <= 4)
                   for (d, v), op in zip(values, specs)))
    if mxu:
        mcols, moks, plan = [], [], []
        for (d, v), op in zip(values, specs):
            vok = K.value_ok(d, v, ok)
            if op == "size":
                plan.append(("size", len(mcols), None))
                mcols.append(jnp.ones((cap,), jnp.float32))
                moks.append(ok)
                continue
            cnt_idx = len(mcols)
            mcols.append(jnp.ones((cap,), jnp.float32))
            moks.append(vok)
            if op == "count":
                plan.append(("count", cnt_idx, None))
            else:
                s_idx = len(mcols)
                mcols.append(d.astype(jnp.float32))
                moks.append(vok)
                plan.append((op, cnt_idx, s_idx))
        live = seg < ng_cap
        sums = PK.dense_accumulate(
            jnp.where(live, seg, 0).astype(jnp.int32), mcols,
            [m & live for m in moks], ng_cap)
        gvals = []
        for op, cnt_idx, s_idx in plan:
            if op in ("size", "count"):
                gvals.append((sums[cnt_idx].astype(jnp.int64), None))
            elif op == "sum":
                gvals.append((sums[s_idx], None))
            else:  # mean
                cnt = sums[cnt_idx]
                m = sums[s_idx] / jnp.maximum(cnt, 1.0)
                gvals.append((jnp.where(cnt > 0, m, jnp.nan), None))
        gvals = tuple(gvals)
    else:
        gvals = tuple(_segment_agg(op, data, valid, seg, ok, ng_cap)
                      for (data, valid), op in zip(values, specs))
    return gkeys, gvals, gvalid


@bounded_jit(static_argnames=("out_capacity",))
def _hashed_sort_groups(gkeys, gvals, gvalid, out_capacity: int):
    """Sort the group table by keys ascending and emit [out_capacity]
    outputs packed at the front (pandas sort=True)."""
    ng_cap = gvalid.shape[0]
    operands: list = []
    for a in gkeys:
        operands.extend(SE.key_operands(a, None, padmask=gvalid))
    nko = len(operands)
    operands.append(jnp.arange(ng_cap))
    gperm = lax.sort(tuple(operands), num_keys=nko, is_stable=True)[-1]

    def scatter(a):
        z = jnp.zeros((out_capacity,), dtype=a.dtype)
        src = a[gperm]
        m = min(ng_cap, out_capacity)
        return z.at[:m].set(src[:m])

    out_keys = tuple((scatter(a), None) for a in gkeys)
    out_vals = tuple((scatter(d), None if v is None else scatter(v))
                     for d, v in gvals)
    return out_keys, out_vals


def groupby_local_hashed_static(arrays, count, specs: Tuple[str, ...],
                                out_capacity: int, num_keys: int):
    """Fully-traced hash groupby for use INSIDE shard_map/jit bodies
    (distributed stage 1): same contract as `groupby_local` plus a
    traced `unresolved` flag, with the group segment space fixed at
    `out_capacity` instead of host-synced from the live group count
    (no host round-trip is possible inside a trace). The caller must
    guarantee out_capacity ≥ the true group count — with
    out_capacity == row capacity that holds by construction.

    Returns (out_keys, out_vals, n_groups, unresolved)."""
    seg, group_row, ok, n_groups, unresolved = _hashed_claim(
        arrays[:num_keys], count)
    gkeys, gvals, gvalid = _hashed_agg(arrays, seg, group_row, ok, specs,
                                       num_keys, out_capacity)
    out_keys, out_vals = _hashed_sort_groups(gkeys, gvals, gvalid,
                                             out_capacity)
    return out_keys, out_vals, n_groups, unresolved


def groupby_local_hashed(arrays, count, specs: Tuple[str, ...],
                         out_capacity: int, num_keys: int):
    """Local groupby via the scatter-claim hash table (ops/hashtable.py)
    instead of a full-row sort: rows claim dense group ids in a few
    scatter/gather rounds, aggregates run as segment reductions (or the
    Pallas MXU one-hot accumulate when the group count fits) over the
    UNSORTED rows, and only the ~n_groups-row group table is sorted to
    restore pandas' key-ascending output — O(U log U) instead of
    O(N log N) with U = number of groups (the reference's hash-groupby
    advantage, bodo/libs/groupby/_groupby.cpp, realized with XLA
    scatters instead of serial chains).

    Same contract as groupby_local, plus an `unresolved` flag: True
    means the probe-round cap was hit (pathological input) and the
    caller must fall back to the sort kernel."""
    from bodo_tpu.table.table import round_capacity

    seg, group_row, ok, n_groups, unresolved = _hashed_claim(
        arrays[:num_keys], count)
    ng, unres = jax.device_get((n_groups, unresolved))
    if bool(unres):
        return None, None, 0, True
    cap = arrays[0][0].shape[0]
    ng_cap = min(round_capacity(max(int(ng), 1)), cap)
    gkeys, gvals, gvalid = _hashed_agg(arrays, seg, group_row, ok, specs,
                                       num_keys, ng_cap)
    out_keys, out_vals = _hashed_sort_groups(gkeys, gvals, gvalid,
                                             out_capacity)
    return out_keys, out_vals, int(ng), False
