"""Pallas TPU kernels for relational hot paths.

The first kernel family targets the dense groupby accumulate: on TPU,
XLA lowers `segment_sum` with random slot ids to scatter-adds, which
serialize on the VPU. For small slot spaces the MXU is the right unit —
aggregation by one-hot matmul: a [BLK, K] one-hot of the slot codes
contracted against the value block accumulates all columns of a block in
one 128x128-systolic pass (the standard TPU histogram/segment-reduce
recipe). This is the TPU-native replacement for the reference's
hash-table accumulate loop (bodo/libs/groupby/_groupby.cpp update step).

Kernels run on TPU only (gated by `use_pallas()`); every caller keeps an
XLA `segment_sum` fallback, and correctness is tested on CPU through
`interpret=True`.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# row block per grid step: onehot f32 [BLK, K<=MAX_SLOTS] must fit VMEM
_BLK = 512
MAX_MATMUL_SLOTS = 4096

_I0 = np.int32(0)  # int32 BlockSpec index constant (see in_specs comment)

# test hook: run kernels through the pallas interpreter on CPU
FORCE_INTERPRET = False


# set when a kernel fails to compile/run on the actual backend: callers
# permanently fall back to the XLA path for the rest of the process
_runtime_disabled = False

# number of times the pallas MXU path was TRACED into a jitted groupby
# or fused pipeline stage (trace-time, not per-execution:
# dense_accumulate is only called from inside jit-compiled bodies, so
# this counts compiled-in engagements — interpret-mode traces included,
# since FORCE_INTERPRET runs the same kernel through the pallas
# interpreter; bench.py's hardware proof is the separate timed
# _pallas_proof run). Exported as pallas_traced_into_pipeline.
trace_count = 0

# per-kernel-family trace engagement (same trace-time semantics as
# trace_count; bench.py surfaces these as pallas:<family> counters so
# the probe/partition/decode kernels each prove engagement separately)
trace_counts = {"groupby": 0, "gather": 0, "probe": 0, "partition": 0,
                "decode": 0, "range": 0}


def _engage(family: str) -> None:
    global trace_count
    trace_count += 1
    trace_counts[family] = trace_counts.get(family, 0) + 1


def reset_trace_counts() -> None:
    for k in trace_counts:
        trace_counts[k] = 0


def disable_runtime(reason: str) -> None:
    global _runtime_disabled
    _runtime_disabled = True
    import sys
    print(f"[bodo_tpu] pallas kernels disabled: {reason}", file=sys.stderr)


def use_pallas() -> bool:
    """Pallas kernels engage only on real TPU backends."""
    if _runtime_disabled:
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# one MXU kernel, few (n_slots, n_cols) signatures per query shape
# shardcheck: ignore[unregistered-jit]
@functools.partial(jax.jit,
                   static_argnames=("n_slots", "n_cols", "interpret"))
def matmul_groupby_sum(codes, vals, n_slots: int, n_cols: int,
                       interpret: bool = False):
    """Sum `vals` ([N, n_cols] f32, pre-masked) into `n_slots` groups via
    one-hot MXU contraction. codes: int32 [N] in [0, n_slots); rows to be
    ignored must carry zeroed vals (any code). Returns [n_slots, n_cols]
    f32 sums."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = codes.shape[0]
    k_pad = _round_up(max(n_slots, 128), 128)
    c_pad = _round_up(max(n_cols, 8), 8)
    n_pad = _round_up(max(n, _BLK), _BLK)
    if n_pad != n:
        codes = jnp.concatenate(
            [codes, jnp.zeros((n_pad - n,), codes.dtype)])
        vals = jnp.concatenate(
            [vals, jnp.zeros((n_pad - n, vals.shape[1]), vals.dtype)])
    if c_pad != vals.shape[1]:
        vals = jnp.concatenate(
            [vals, jnp.zeros((vals.shape[0], c_pad - vals.shape[1]),
                             vals.dtype)], axis=1)
    # codes ride as a 2-D [N, 1] block: 1-D BlockSpecs fail Mosaic
    # legalization on current libtpu toolchains (func.return on the
    # implicit scalar layout), and TPU vregs are 2-D (8x128) anyway
    codes2 = codes[:, None]

    def kernel(codes_ref, vals_ref, out_ref, acc_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        codes_blk = codes_ref[:]                      # [BLK, 1]
        onehot = (codes_blk ==
                  jax.lax.broadcasted_iota(jnp.int32, (1, k_pad), 1)
                  ).astype(jnp.float32)               # [BLK, K]
        # [C, BLK] @ [BLK, K] -> [C, K] on the MXU. HIGHEST precision:
        # the default bf16 MXU pass rounds the f32 values (~0.4% rel
        # error on sums); the one-hot side is exact either way, so the
        # bf16x3 decomposition restores ~f32 accuracy for the val side
        acc_ref[:] += jax.lax.dot_general(
            vals_ref[:].T, onehot,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

        @pl.when(step == pl.num_programs(0) - 1)
        def _flush():
            out_ref[:] = acc_ref[:]

    # traced inside the jitted matmul_groupby_sum above — cached by
    # its jit signature  # shardcheck: ignore[unregistered-jit]
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // _BLK,),
        # index-map constants must be int32: under jax_enable_x64 (which
        # the engine needs for int64 ticks) a bare Python 0 becomes an
        # i64, and Mosaic fails to legalize the mixed (i32, i64) return
        in_specs=[
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
            pl.BlockSpec((_BLK, c_pad), lambda i: (i, _I0)),
        ],
        out_specs=pl.BlockSpec((c_pad, k_pad), lambda i: (_I0, _I0)),
        out_shape=jax.ShapeDtypeStruct((c_pad, k_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((c_pad, k_pad), jnp.float32)],
        interpret=interpret,
    )(codes2, vals)
    return out[:n_cols, :n_slots].T                   # [n_slots, n_cols]


# one-hot gathers are exact in f32 only while the gathered values fit
# the 24-bit mantissa; callers carry row indices, so this bounds nrows
MAX_GATHER_VALUE = 1 << 24


# shardcheck: ignore[unregistered-jit]
@functools.partial(jax.jit, static_argnames=("n_slots", "interpret"))
def _matmul_gather_kernel(codes, lut, n_slots: int,
                          interpret: bool = False):
    """lut[codes] by one-hot MXU contraction: a [BLK, K] one-hot of the
    slot codes contracted against the f32 LUT column. codes: int32 [N]
    in [0, n_slots); lut: int32 [n_slots] with values in
    (-MAX_GATHER_VALUE, MAX_GATHER_VALUE) so the f32 pass is exact.
    Returns int32 [N]."""
    from jax.experimental import pallas as pl

    n = codes.shape[0]
    k_pad = _round_up(max(n_slots, 128), 128)
    n_pad = _round_up(max(n, _BLK), _BLK)
    if n_pad != n:
        codes = jnp.concatenate(
            [codes, jnp.zeros((n_pad - n,), codes.dtype)])
    lutf = jnp.zeros((k_pad, 1), jnp.float32).at[:n_slots, 0].set(
        lut.astype(jnp.float32))
    codes2 = codes[:, None]                           # 2-D, see above

    def kernel(codes_ref, lut_ref, out_ref):
        codes_blk = codes_ref[:]                      # [BLK, 1]
        onehot = (codes_blk ==
                  jax.lax.broadcasted_iota(jnp.int32, (1, k_pad), 1)
                  ).astype(jnp.float32)               # [BLK, K]
        # [BLK, K] @ [K, 1] -> [BLK, 1]: exactly one lut row per code,
        # so the f32 contraction reproduces the int32 value exactly
        out_ref[:] = jax.lax.dot_general(
            onehot, lut_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

    # shardcheck: ignore[unregistered-jit]
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // _BLK,),
        in_specs=[
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
            pl.BlockSpec((k_pad, 1), lambda i: (_I0, _I0)),
        ],
        out_specs=pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        interpret=interpret,
    )(codes2, lutf)
    return out[:n, 0].astype(jnp.int32)


def matmul_gather(codes, lut, interpret: Optional[bool] = None):
    """Gather ``lut[codes]`` (the dense-LUT hash-probe lookup step).

    TPU (or interpret=True) with a LUT small enough for the one-hot
    MXU pass: the pallas kernel above. Elsewhere: the plain XLA gather.
    Callers must keep lut values within (-MAX_GATHER_VALUE,
    MAX_GATHER_VALUE) — they are row indices plus the -1 empty marker,
    so this caps the build side at 16M rows (checked by the caller's
    gate, not here)."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    if (use_pallas() or interp) and lut.shape[0] <= MAX_MATMUL_SLOTS:
        _engage("gather")
        return _matmul_gather_kernel(codes, lut, lut.shape[0],
                                     interpret=interp)
    return lut[codes]


def bucket_counts(dest, ok, num_buckets: int,
                  interpret: Optional[bool] = None):
    """Per-destination row histogram (the bucket-partition counting
    step of the fixed-capacity shuffle): count rows with ok set per
    dest shard. On TPU the scatter-add that XLA lowers segment_sum to
    serializes on the VPU, so this routes through the same one-hot MXU
    accumulate as the dense groupby. Exact while the per-bucket count
    stays under MAX_GATHER_VALUE (f32 mantissa), which the row-count
    gate guarantees. Returns int32 [num_buckets]."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    if ((use_pallas() or interp) and num_buckets <= MAX_MATMUL_SLOTS
            and dest.shape[0] < MAX_GATHER_VALUE):
        _engage("partition")
        vals = ok.astype(jnp.float32)[:, None]
        sums = matmul_groupby_sum(dest.astype(jnp.int32), vals,
                                  num_buckets, 1, interpret=interp)
        return sums[:, 0].astype(jnp.int32)
    return jax.ops.segment_sum(ok.astype(jnp.int32),
                               dest.astype(jnp.int32),
                               num_segments=num_buckets)


def dense_accumulate(codes, cols: Sequence, ok_masks: Sequence,
                     n_slots: int, interpret: Optional[bool] = None):
    """Sum each (column, mask) pair into dense slots.

    TPU (or interpret=True): one fused MXU one-hot matmul over all
    columns. Elsewhere: per-column XLA segment_sum (scatter). Returns a
    list of f32/f64 [n_slots] arrays aligned with `cols`."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    if (use_pallas() or interp) and n_slots <= MAX_MATMUL_SLOTS:
        _engage("groupby")
        vals = jnp.stack(
            [jnp.where(ok, c, 0).astype(jnp.float32)
             for c, ok in zip(cols, ok_masks)], axis=1)
        sums = matmul_groupby_sum(codes, vals, n_slots, len(cols),
                                  interpret=interp)
        return [sums[:, i] for i in range(len(cols))]
    return [jax.ops.segment_sum(jnp.where(ok, c, 0).astype(jnp.float64),
                                codes, num_segments=n_slots)
            for c, ok in zip(cols, ok_masks)]


# ---------------------------------------------------------------------------
# hash-probe loop (open-addressing slot search on the MXU)
# ---------------------------------------------------------------------------

def _split_u64_planes(codes: Sequence) -> jax.Array:
    """Split uint64 code columns into f32 16-bit planes [N, 4*len].

    Two uint64s are equal iff all four of their 16-bit planes are equal,
    and every plane value (< 2^16) is exact in f32 — so a one-hot MXU
    gather of the planes supports exact 64-bit key comparison."""
    planes = []
    for c in codes:
        for k in range(4):
            planes.append(((c >> np.uint64(16 * k))
                           & np.uint64(0xFFFF)).astype(jnp.float32))
    return jnp.stack(planes, axis=1)


# shardcheck: ignore[unregistered-jit]
@functools.partial(jax.jit, static_argnames=("T", "n_planes",
                                             "max_rounds", "interpret"))
def _hash_probe_kernel(h_m, step_m, probe_planes, active0, slot_tab,
                       T: int, n_planes: int, max_rounds: int,
                       interpret: bool = False):
    """Open-addressing probe loop in one kernel: each round gathers the
    probed slot's (owner, key planes) row with a single one-hot MXU
    matmul and resolves hits/misses in registers — the whole double-hash
    walk stays on-chip instead of one XLA gather dispatch per round.

    h_m/step_m: int32 [N] hash and step already reduced mod T (the probe
    sequence (h + r*step) mod T only needs the low bits, so int32
    arithmetic is exact). slot_tab: f32 [T, 1+n_planes] — column 0 is
    the owning build row per slot (-1 empty), the rest are the slot
    key's 16-bit planes. Returns (idx f32 [N,1], still_active f32
    [N,1])."""
    from jax.experimental import pallas as pl

    n = h_m.shape[0]
    k_pad = _round_up(max(T, 128), 128)
    c_pad = _round_up(max(1 + n_planes, 128), 128)
    p_pad = _round_up(max(n_planes, 128), 128)
    n_pad = _round_up(max(n, _BLK), _BLK)

    def pad_rows(a):
        if a.shape[0] == n_pad:
            return a
        return jnp.concatenate(
            [a, jnp.zeros((n_pad - a.shape[0],) + a.shape[1:], a.dtype)])

    h2 = pad_rows(h_m[:, None])
    s2 = pad_rows(step_m[:, None])
    pp = pad_rows(jnp.pad(probe_planes,
                          ((0, 0), (0, p_pad - n_planes))))
    act = pad_rows(active0.astype(jnp.float32)[:, None])
    tab = jnp.zeros((k_pad, c_pad), jnp.float32)
    tab = tab.at[:T, :1 + n_planes].set(slot_tab)
    maskT = np.int32(T - 1)

    def kernel(hm_ref, sm_ref, pp_ref, act_ref, tab_ref, idx_ref,
               unres_ref):
        hm = hm_ref[:]
        sm = sm_ref[:]
        ppb = pp_ref[:]

        def cond(st):
            r, idx, active = st
            return (r < max_rounds) & jnp.any(active > 0)

        def body(st):
            r, idx, active = st
            p = jnp.bitwise_and(hm + r * sm, maskT)         # [BLK, 1]
            onehot = (p == jax.lax.broadcasted_iota(
                jnp.int32, (1, k_pad), 1)).astype(jnp.float32)
            g = jax.lax.dot_general(
                onehot, tab_ref[:],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)        # [BLK, C]
            o = g[:, 0:1]
            eq = o >= 0
            for j in range(n_planes):
                eq = eq & (g[:, 1 + j:2 + j] == ppb[:, j:j + 1])
            live = active > 0
            hit = live & eq
            miss = live & (o < 0)
            idx = jnp.where(hit, o, idx)
            active = jnp.where(hit | miss, 0.0, active)
            return r + np.int32(1), idx, active

        idx0 = jnp.full(hm.shape, -1.0, jnp.float32)
        _r, idx, active = jax.lax.while_loop(
            cond, body, (np.int32(0), idx0, act_ref[:]))
        idx_ref[:] = idx
        unres_ref[:] = active

    # shardcheck: ignore[unregistered-jit]
    idx, unres = pl.pallas_call(
        kernel,
        grid=(n_pad // _BLK,),
        in_specs=[
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
            pl.BlockSpec((_BLK, p_pad), lambda i: (i, _I0)),
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
            pl.BlockSpec((k_pad, c_pad), lambda i: (_I0, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(h2, s2, pp, act, tab)
    return idx[:n, 0], unres[:n, 0]


def hash_probe(build_codes: Sequence, owner, probe_codes: Sequence, ok,
               h, step, T: int, max_rounds: int,
               interpret: Optional[bool] = None):
    """Pallas route for ops/hashtable.probe_slots: the open-addressing
    slot search as ONE kernel (per-round slot gather + 64-bit key
    compare on the MXU via 16-bit planes). `h`/`step` are the caller's
    uint64 double-hash sequence parameters. Returns (idx int32 [N],
    unresolved bool) or None when the gate is closed (caller keeps its
    XLA while_loop)."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    if not ((use_pallas() or interp) and T <= MAX_MATMUL_SLOTS
            and T // 2 < MAX_GATHER_VALUE):
        return None
    _engage("probe")
    maskT = np.uint64(T - 1)
    h_m = (h & maskT).astype(jnp.int32)
    step_m = (step & maskT).astype(jnp.int32)
    # slot table: owner + the slot key's planes (gathered once, XLA)
    osafe = jnp.maximum(owner, 0)
    slot_planes = _split_u64_planes([c[osafe] for c in build_codes])
    slot_tab = jnp.concatenate(
        [owner.astype(jnp.float32)[:, None], slot_planes], axis=1)
    probe_planes = _split_u64_planes(list(probe_codes))
    idx, unres = _hash_probe_kernel(
        h_m, step_m, probe_planes, ok, slot_tab, T,
        4 * len(probe_codes), max_rounds, interpret=interp)
    return idx.astype(jnp.int32), jnp.any(unres > 0)


# ---------------------------------------------------------------------------
# bucket partition scatter (stable in-bucket rank without a sort)
# ---------------------------------------------------------------------------

# shardcheck: ignore[unregistered-jit]
@functools.partial(jax.jit, static_argnames=("num_buckets", "interpret"))
def _partition_rank_kernel(dest, ok, num_buckets: int,
                           interpret: bool = False):
    """Stable in-bucket rank per row + per-bucket counts in one grid
    pass: a block's in-block exclusive rank is a strict-lower-triangular
    matmul against the block's one-hot destination matrix, and a running
    per-bucket base rides in VMEM scratch across blocks (sequential
    grid). Replaces the stable sort the XLA fallback uses to derive
    scatter positions. Exact while ranks stay under the f32 mantissa
    (callers gate rows < MAX_GATHER_VALUE)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = dest.shape[0]
    k_pad = _round_up(max(num_buckets, 128), 128)
    n_pad = _round_up(max(n, _BLK), _BLK)
    if n_pad != n:
        dest = jnp.concatenate(
            [dest, jnp.zeros((n_pad - n,), dest.dtype)])
        ok = jnp.concatenate([ok, jnp.zeros((n_pad - n,), bool)])
    dest2 = dest.astype(jnp.int32)[:, None]
    ok2 = ok.astype(jnp.float32)[:, None]

    def kernel(dest_ref, ok_ref, rank_ref, cnt_ref, base_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            base_ref[:] = jnp.zeros_like(base_ref)

        d = dest_ref[:]                                   # [BLK, 1]
        okf = ok_ref[:]                                   # [BLK, 1]
        onehot = (d == jax.lax.broadcasted_iota(
            jnp.int32, (1, k_pad), 1)).astype(jnp.float32) * okf
        row = jax.lax.broadcasted_iota(jnp.int32, (_BLK, _BLK), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (_BLK, _BLK), 1)
        tri = (row > col).astype(jnp.float32)
        # earlier in-block rows per bucket, then select own column
        prefix = jax.lax.dot_general(
            tri, onehot, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)          # [BLK, K]
        rank_in = jnp.sum(prefix * onehot, axis=1, keepdims=True)
        base_at = jax.lax.dot_general(
            onehot, base_ref[:].T,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)          # [BLK, 1]
        rank_ref[:] = jnp.where(okf > 0, rank_in + base_at, -1.0)
        base_ref[:] += jnp.sum(onehot, axis=0, keepdims=True)

        @pl.when(step == pl.num_programs(0) - 1)
        def _flush():
            cnt_ref[:] = base_ref[:]

    # shardcheck: ignore[unregistered-jit]
    rank, cnt = pl.pallas_call(
        kernel,
        grid=(n_pad // _BLK,),
        in_specs=[
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
            pl.BlockSpec((1, k_pad), lambda i: (_I0, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, k_pad), jnp.float32)],
        interpret=interpret,
    )(dest2, ok2)
    return (rank[:n, 0].astype(jnp.int32),
            cnt[0, :num_buckets].astype(jnp.int32))


def partition_rank(dest, ok, num_buckets: int,
                   interpret: Optional[bool] = None):
    """Pallas route for the bucket-partition scatter: per-row stable
    in-bucket rank plus per-bucket counts (parallel/shuffle.bucket_rows
    derives scatter positions from this instead of a stable sort; the
    sort sample-partition step shares it). Returns (rank int32 [N],
    counts int32 [num_buckets]) or None when the gate is closed."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    if not ((use_pallas() or interp) and num_buckets <= MAX_MATMUL_SLOTS
            and dest.shape[0] < MAX_GATHER_VALUE):
        return None
    _engage("partition")
    return _partition_rank_kernel(dest.astype(jnp.int32), ok,
                                  num_buckets, interpret=interp)


# ---------------------------------------------------------------------------
# RLE/bit-packed hybrid run expansion + dictionary gather (device decode)
# ---------------------------------------------------------------------------

# run-table bound for the in-kernel searchsorted (a [BLK, R] compare)
MAX_EXPAND_RUNS = 2048


# shardcheck: ignore[unregistered-jit]
@functools.partial(jax.jit, static_argnames=("bw", "n_bucket", "n_runs",
                                             "interpret"))
def _hybrid_expand_kernel(data, starts, is_rle, vals, bits, bw: int,
                          n_bucket: int, n_runs: int,
                          interpret: bool = False):
    """Hybrid RLE/bit-packed run expansion in one kernel: output index →
    owning run via an in-register compare-count over the (small) run
    table, run fields gathered by one-hot MXU matmul, bit-packed values
    extracted through a 4-byte little-endian gather window. The byte
    gathers use dynamic indexing (jnp.take) — interpret-proven; a
    backend that rejects it falls back via disable_runtime."""
    from jax.experimental import pallas as pl

    r_pad = _round_up(max(n_runs, 128), 128)
    c_pad = 128
    n_pad = _round_up(max(n_bucket, _BLK), _BLK)
    nb = data.shape[0]
    sentinel = np.float32(n_bucket + 1)
    st = jnp.full((1, r_pad), sentinel, jnp.float32).at[0, :n_runs].set(
        starts.astype(jnp.float32))
    tab = jnp.zeros((r_pad, c_pad), jnp.float32)
    tab = tab.at[:n_runs, 0].set(starts.astype(jnp.float32))
    tab = tab.at[:n_runs, 1].set(is_rle.astype(jnp.float32))
    tab = tab.at[:n_runs, 2].set(vals.astype(jnp.float32))
    tab = tab.at[:n_runs, 3].set(bits.astype(jnp.float32))
    data2 = data.astype(jnp.uint32)[:, None]

    def kernel(data_ref, st_ref, tab_ref, out_ref):
        step = pl.program_id(0)
        i = (step * _BLK + jax.lax.broadcasted_iota(
            jnp.int32, (_BLK, 1), 0)).astype(jnp.float32)
        # searchsorted(starts, i, 'right') - 1 == count(starts <= i) - 1
        cnt = jnp.sum((st_ref[:] <= i).astype(jnp.float32), axis=1,
                      keepdims=True)
        r = jnp.clip(cnt - 1.0, 0.0, np.float32(n_runs - 1))
        onehot = (r == jax.lax.broadcasted_iota(
            jnp.float32, (1, r_pad), 1)).astype(jnp.float32)
        g = jax.lax.dot_general(
            onehot, tab_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)          # [BLK, C]
        start_r = g[:, 0:1]
        isrle_r = g[:, 1:2]
        val_r = g[:, 2:3]
        bit_r = g[:, 3:4]
        if bw > 0:
            rel = i - start_r
            bp = (bit_r + rel * np.float32(bw)).astype(jnp.int32)
            byte0 = bp >> 3
            dat = data_ref[:]                              # [nb, 1]
            w = jnp.take(dat, jnp.clip(byte0, 0, nb - 1),
                         axis=0)[:, :, 0]
            w = w | (jnp.take(dat, jnp.clip(byte0 + 1, 0, nb - 1),
                              axis=0)[:, :, 0] << 8)
            w = w | (jnp.take(dat, jnp.clip(byte0 + 2, 0, nb - 1),
                              axis=0)[:, :, 0] << 16)
            w = w | (jnp.take(dat, jnp.clip(byte0 + 3, 0, nb - 1),
                              axis=0)[:, :, 0] << 24)
            packed = ((w >> jnp.bitwise_and(bp, 7).astype(jnp.uint32))
                      & np.uint32((1 << bw) - 1)).astype(jnp.float32)
        else:
            packed = jnp.zeros((_BLK, 1), jnp.float32)
        out_ref[:] = jnp.where(isrle_r > 0, val_r, packed)

    # shardcheck: ignore[unregistered-jit]
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // _BLK,),
        in_specs=[
            pl.BlockSpec((nb, 1), lambda i: (_I0, _I0)),
            pl.BlockSpec((1, r_pad), lambda i: (_I0, _I0)),
            pl.BlockSpec((r_pad, c_pad), lambda i: (_I0, _I0)),
        ],
        out_specs=pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        interpret=interpret,
    )(data2, st, tab)
    return out[:n_bucket, 0].astype(jnp.int32)


def hybrid_expand(data, starts, is_rle, vals, bits, bw: int,
                  n_bucket: int, interpret: Optional[bool] = None):
    """Pallas route for io/device_decode's hybrid run expansion (the
    RLE/bit-packed decode inner loop — dict index streams, RLE booleans,
    definition levels). Inputs are the already-padded device run tables.
    Returns int32 [n_bucket] expanded values, or None when the gate is
    closed (caller keeps the XLA searchsorted body)."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    n_runs = starts.shape[0]
    if not ((use_pallas() or interp) and n_runs <= MAX_EXPAND_RUNS
            and n_bucket < MAX_GATHER_VALUE
            and data.shape[0] * 8 < MAX_GATHER_VALUE and 0 <= bw <= 24):
        return None
    _engage("decode")
    return _hybrid_expand_kernel(data, starts, is_rle, vals, bits, bw,
                                 n_bucket, n_runs, interpret=interp)


def dict_gather(codes, lut, interpret: Optional[bool] = None):
    """Pallas dictionary gather for decode: ``lut[codes]`` through the
    one-hot MXU kernel (the string-dict rank remap and small numeric
    dictionaries route here). LUT values must fit the f32 mantissa —
    rank LUTs always do (ranks < dictionary length). Returns int32 [N]
    or None when the gate is closed."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    if not ((use_pallas() or interp)
            and lut.shape[0] <= MAX_MATMUL_SLOTS):
        return None
    _engage("decode")
    return _matmul_gather_kernel(codes, lut, lut.shape[0],
                                 interpret=interp)


# ---------------------------------------------------------------------------
# radix/range partition step (uint64 keys via 16-bit planes; ops/sort.py)
# ---------------------------------------------------------------------------

# shardcheck: ignore[unregistered-jit]
@functools.partial(jax.jit, static_argnames=("n_spl", "interpret"))
def _range_partition_kernel(pk_planes, spl_planes, spl_valid, n_spl: int,
                            interpret: bool = False):
    """dest = #(splitters <= pk) by lexicographic 16-bit-plane compare
    (the radix step of the sample sort's range partition): uint64 order
    decided plane-by-plane from the high radix digit down, all in f32
    vector compares — no uint64 arithmetic in the kernel."""
    from jax.experimental import pallas as pl

    n = pk_planes.shape[0]
    s_pad = _round_up(max(n_spl, 128), 128)
    n_pad = _round_up(max(n, _BLK), _BLK)
    if n_pad != n:
        pk_planes = jnp.concatenate(
            [pk_planes, jnp.zeros((n_pad - n, 4), pk_planes.dtype)])
    spl = jnp.zeros((4, s_pad), jnp.float32)
    spl = spl.at[:, :n_spl].set(spl_planes.T)
    sv = jnp.zeros((1, s_pad), jnp.float32).at[0, :n_spl].set(
        spl_valid.astype(jnp.float32))

    def kernel(pp_ref, spl_ref, sv_ref, out_ref):
        pp = pp_ref[:]                                    # [BLK, 4]
        gt = jnp.zeros((_BLK, s_pad), jnp.float32)
        eq = jnp.ones((_BLK, s_pad), jnp.float32)
        for k in (3, 2, 1, 0):                            # high plane first
            pkk = pp[:, k:k + 1]                          # [BLK, 1]
            sk = spl_ref[k:k + 1, :]                      # [1, S]
            gt = jnp.maximum(gt, eq * (pkk > sk).astype(jnp.float32))
            eq = eq * (pkk == sk).astype(jnp.float32)
        ge = jnp.maximum(gt, eq) * sv_ref[:]              # pk >= splitter
        out_ref[:] = jnp.sum(ge, axis=1, keepdims=True)

    # shardcheck: ignore[unregistered-jit]
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // _BLK,),
        in_specs=[
            pl.BlockSpec((_BLK, 4), lambda i: (i, _I0)),
            pl.BlockSpec((4, s_pad), lambda i: (_I0, _I0)),
            pl.BlockSpec((1, s_pad), lambda i: (_I0, _I0)),
        ],
        out_specs=pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        interpret=interpret,
    )(pk_planes, spl, sv)
    return out[:n, 0].astype(jnp.int32)


def range_partition(pk, splitters, interpret: Optional[bool] = None):
    """Pallas route for the sample sort's destination assignment:
    ``searchsorted(splitters, pk, side='right')`` over uint64 partition
    keys, decided by 16-bit radix planes in-kernel. Returns int32 [N]
    destinations or None when the gate is closed."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    n_spl = splitters.shape[0]
    if not ((use_pallas() or interp) and 0 < n_spl <= MAX_MATMUL_SLOTS):
        return None
    _engage("range")
    pk_planes = _split_u64_planes([pk])
    spl_planes = _split_u64_planes([splitters])
    return _range_partition_kernel(pk_planes, spl_planes,
                                   jnp.ones((n_spl,), bool), n_spl,
                                   interpret=interp)
