"""Pallas TPU kernels for relational hot paths.

The first kernel family targets the dense groupby accumulate: on TPU,
XLA lowers `segment_sum` with random slot ids to scatter-adds, which
serialize on the VPU. For small slot spaces the MXU is the right unit —
aggregation by one-hot matmul: a [BLK, K] one-hot of the slot codes
contracted against the value block accumulates all columns of a block in
one 128x128-systolic pass (the standard TPU histogram/segment-reduce
recipe). This is the TPU-native replacement for the reference's
hash-table accumulate loop (bodo/libs/groupby/_groupby.cpp update step).

Kernels run on TPU only (gated by `use_pallas()`); every caller keeps an
XLA `segment_sum` fallback, and correctness is tested on CPU through
`interpret=True`.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# row block per grid step: onehot f32 [BLK, K<=MAX_SLOTS] must fit VMEM
_BLK = 512
MAX_MATMUL_SLOTS = 4096

_I0 = np.int32(0)  # int32 BlockSpec index constant (see in_specs comment)

# test hook: run kernels through the pallas interpreter on CPU
FORCE_INTERPRET = False


# set when a kernel fails to compile/run on the actual backend: callers
# permanently fall back to the XLA path for the rest of the process
_runtime_disabled = False

# number of times the pallas MXU path was TRACED into a jitted groupby
# or fused pipeline stage (trace-time, not per-execution:
# dense_accumulate is only called from inside jit-compiled bodies, so
# this counts compiled-in engagements — interpret-mode traces included,
# since FORCE_INTERPRET runs the same kernel through the pallas
# interpreter; bench.py's hardware proof is the separate timed
# _pallas_proof run). Exported as pallas_traced_into_pipeline.
trace_count = 0


def disable_runtime(reason: str) -> None:
    global _runtime_disabled
    _runtime_disabled = True
    import sys
    print(f"[bodo_tpu] pallas kernels disabled: {reason}", file=sys.stderr)


def use_pallas() -> bool:
    """Pallas kernels engage only on real TPU backends."""
    if _runtime_disabled:
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# one MXU kernel, few (n_slots, n_cols) signatures per query shape
# shardcheck: ignore[unregistered-jit]
@functools.partial(jax.jit,
                   static_argnames=("n_slots", "n_cols", "interpret"))
def matmul_groupby_sum(codes, vals, n_slots: int, n_cols: int,
                       interpret: bool = False):
    """Sum `vals` ([N, n_cols] f32, pre-masked) into `n_slots` groups via
    one-hot MXU contraction. codes: int32 [N] in [0, n_slots); rows to be
    ignored must carry zeroed vals (any code). Returns [n_slots, n_cols]
    f32 sums."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = codes.shape[0]
    k_pad = _round_up(max(n_slots, 128), 128)
    c_pad = _round_up(max(n_cols, 8), 8)
    n_pad = _round_up(max(n, _BLK), _BLK)
    if n_pad != n:
        codes = jnp.concatenate(
            [codes, jnp.zeros((n_pad - n,), codes.dtype)])
        vals = jnp.concatenate(
            [vals, jnp.zeros((n_pad - n, vals.shape[1]), vals.dtype)])
    if c_pad != vals.shape[1]:
        vals = jnp.concatenate(
            [vals, jnp.zeros((vals.shape[0], c_pad - vals.shape[1]),
                             vals.dtype)], axis=1)
    # codes ride as a 2-D [N, 1] block: 1-D BlockSpecs fail Mosaic
    # legalization on current libtpu toolchains (func.return on the
    # implicit scalar layout), and TPU vregs are 2-D (8x128) anyway
    codes2 = codes[:, None]

    def kernel(codes_ref, vals_ref, out_ref, acc_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        codes_blk = codes_ref[:]                      # [BLK, 1]
        onehot = (codes_blk ==
                  jax.lax.broadcasted_iota(jnp.int32, (1, k_pad), 1)
                  ).astype(jnp.float32)               # [BLK, K]
        # [C, BLK] @ [BLK, K] -> [C, K] on the MXU. HIGHEST precision:
        # the default bf16 MXU pass rounds the f32 values (~0.4% rel
        # error on sums); the one-hot side is exact either way, so the
        # bf16x3 decomposition restores ~f32 accuracy for the val side
        acc_ref[:] += jax.lax.dot_general(
            vals_ref[:].T, onehot,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

        @pl.when(step == pl.num_programs(0) - 1)
        def _flush():
            out_ref[:] = acc_ref[:]

    # traced inside the jitted matmul_groupby_sum above — cached by
    # its jit signature  # shardcheck: ignore[unregistered-jit]
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // _BLK,),
        # index-map constants must be int32: under jax_enable_x64 (which
        # the engine needs for int64 ticks) a bare Python 0 becomes an
        # i64, and Mosaic fails to legalize the mixed (i32, i64) return
        in_specs=[
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
            pl.BlockSpec((_BLK, c_pad), lambda i: (i, _I0)),
        ],
        out_specs=pl.BlockSpec((c_pad, k_pad), lambda i: (_I0, _I0)),
        out_shape=jax.ShapeDtypeStruct((c_pad, k_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((c_pad, k_pad), jnp.float32)],
        interpret=interpret,
    )(codes2, vals)
    return out[:n_cols, :n_slots].T                   # [n_slots, n_cols]


# one-hot gathers are exact in f32 only while the gathered values fit
# the 24-bit mantissa; callers carry row indices, so this bounds nrows
MAX_GATHER_VALUE = 1 << 24


# shardcheck: ignore[unregistered-jit]
@functools.partial(jax.jit, static_argnames=("n_slots", "interpret"))
def _matmul_gather_kernel(codes, lut, n_slots: int,
                          interpret: bool = False):
    """lut[codes] by one-hot MXU contraction: a [BLK, K] one-hot of the
    slot codes contracted against the f32 LUT column. codes: int32 [N]
    in [0, n_slots); lut: int32 [n_slots] with values in
    (-MAX_GATHER_VALUE, MAX_GATHER_VALUE) so the f32 pass is exact.
    Returns int32 [N]."""
    from jax.experimental import pallas as pl

    n = codes.shape[0]
    k_pad = _round_up(max(n_slots, 128), 128)
    n_pad = _round_up(max(n, _BLK), _BLK)
    if n_pad != n:
        codes = jnp.concatenate(
            [codes, jnp.zeros((n_pad - n,), codes.dtype)])
    lutf = jnp.zeros((k_pad, 1), jnp.float32).at[:n_slots, 0].set(
        lut.astype(jnp.float32))
    codes2 = codes[:, None]                           # 2-D, see above

    def kernel(codes_ref, lut_ref, out_ref):
        codes_blk = codes_ref[:]                      # [BLK, 1]
        onehot = (codes_blk ==
                  jax.lax.broadcasted_iota(jnp.int32, (1, k_pad), 1)
                  ).astype(jnp.float32)               # [BLK, K]
        # [BLK, K] @ [K, 1] -> [BLK, 1]: exactly one lut row per code,
        # so the f32 contraction reproduces the int32 value exactly
        out_ref[:] = jax.lax.dot_general(
            onehot, lut_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

    # shardcheck: ignore[unregistered-jit]
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // _BLK,),
        in_specs=[
            pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
            pl.BlockSpec((k_pad, 1), lambda i: (_I0, _I0)),
        ],
        out_specs=pl.BlockSpec((_BLK, 1), lambda i: (i, _I0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        interpret=interpret,
    )(codes2, lutf)
    return out[:n, 0].astype(jnp.int32)


def matmul_gather(codes, lut, interpret: Optional[bool] = None):
    """Gather ``lut[codes]`` (the dense-LUT hash-probe lookup step).

    TPU (or interpret=True) with a LUT small enough for the one-hot
    MXU pass: the pallas kernel above. Elsewhere: the plain XLA gather.
    Callers must keep lut values within (-MAX_GATHER_VALUE,
    MAX_GATHER_VALUE) — they are row indices plus the -1 empty marker,
    so this caps the build side at 16M rows (checked by the caller's
    gate, not here)."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    if (use_pallas() or interp) and lut.shape[0] <= MAX_MATMUL_SLOTS:
        global trace_count
        trace_count += 1
        return _matmul_gather_kernel(codes, lut, lut.shape[0],
                                     interpret=interp)
    return lut[codes]


def bucket_counts(dest, ok, num_buckets: int,
                  interpret: Optional[bool] = None):
    """Per-destination row histogram (the bucket-partition counting
    step of the fixed-capacity shuffle): count rows with ok set per
    dest shard. On TPU the scatter-add that XLA lowers segment_sum to
    serializes on the VPU, so this routes through the same one-hot MXU
    accumulate as the dense groupby. Exact while the per-bucket count
    stays under MAX_GATHER_VALUE (f32 mantissa), which the row-count
    gate guarantees. Returns int32 [num_buckets]."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    if ((use_pallas() or interp) and num_buckets <= MAX_MATMUL_SLOTS
            and dest.shape[0] < MAX_GATHER_VALUE):
        global trace_count
        trace_count += 1
        vals = ok.astype(jnp.float32)[:, None]
        sums = matmul_groupby_sum(dest.astype(jnp.int32), vals,
                                  num_buckets, 1, interpret=interp)
        return sums[:, 0].astype(jnp.int32)
    return jax.ops.segment_sum(ok.astype(jnp.int32),
                               dest.astype(jnp.int32),
                               num_segments=num_buckets)


def dense_accumulate(codes, cols: Sequence, ok_masks: Sequence,
                     n_slots: int, interpret: Optional[bool] = None):
    """Sum each (column, mask) pair into dense slots.

    TPU (or interpret=True): one fused MXU one-hot matmul over all
    columns. Elsewhere: per-column XLA segment_sum (scatter). Returns a
    list of f32/f64 [n_slots] arrays aligned with `cols`."""
    interp = bool(interpret) if interpret is not None else FORCE_INTERPRET
    if (use_pallas() or interp) and n_slots <= MAX_MATMUL_SLOTS:
        global trace_count
        trace_count += 1
        vals = jnp.stack(
            [jnp.where(ok, c, 0).astype(jnp.float32)
             for c, ok in zip(cols, ok_masks)], axis=1)
        sums = matmul_groupby_sum(codes, vals, n_slots, len(cols),
                                  interpret=interp)
        return [sums[:, i] for i in range(len(cols))]
    return [jax.ops.segment_sum(jnp.where(ok, c, 0).astype(jnp.float64),
                                codes, num_segments=n_slots)
            for c, ok in zip(cols, ok_masks)]
