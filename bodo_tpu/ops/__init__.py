"""Relational operator kernels (XLA/jnp + Pallas).

TPU-native equivalents of the reference's C++ kernel layer
(bodo/libs/groupby/, _hash_join.cpp, _array_operations.cpp, streaming/):
segment reductions for groupby, encoded multi-key sorts, compaction-based
filters, sort-merge joins — all static-shape, padded, jit-traceable.
"""
