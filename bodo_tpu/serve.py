"""bodo_tpu.serve — the multi-tenant query-serving client surface.

Thin façade over ``runtime/scheduler.py``: one resident SPMD gang, many
concurrent logical sessions. A client opens a :func:`session`, submits
plan thunks (any callable that runs engine work — a
``df.to_pandas`` lambda, a ``ctx.sql(...)`` call) and gets Futures
back; the scheduler multiplexes them onto the warm gang with fair-share
queueing, admission control from the live health/metrics signals, and
typed backpressure instead of OOM.

    import bodo_tpu
    srv = bodo_tpu.serve.start()
    a = bodo_tpu.serve.session("tenant-a", priority=2.0)
    fut = a.submit(lambda: df.groupby("k").agg(s=("v", "sum")).to_pandas())
    try:
        out = fut.result()
    except bodo_tpu.serve.Overloaded as e:
        time.sleep(e.retry_after_s)   # typed backpressure contract

Sessions share every warm layer the engine has — the fusion/compile
program caches, the SQL plan cache, the persistent AQE stats store and
the semantic result cache — with per-session accounting underneath so
one tenant's huge join cannot evict another tenant's working set.

Knobs: ``BODO_TPU_SERVE_*`` (see config.py) — worker count, queue
bounds, admission thresholds, aging rate, retry-after base.
"""

from __future__ import annotations

from typing import Callable, Optional

from bodo_tpu.runtime.scheduler import (  # noqa: F401 - public re-exports
    AdmissionController,
    AdmissionSignals,
    BackOff,
    Decision,
    Degraded,
    Overloaded,
    QueryFailed,
    Scheduler,
    ServeRejection,
    Session,
    current_session,
    local_signals,
    scheduler,
    session_scope,
    signals_from_health,
    signals_from_metrics,
)
from bodo_tpu.runtime.views import (  # noqa: F401 - continuous queries
    MAINTENANCE_SESSION,
    Subscription,
)

__all__ = [
    "start", "stop", "drain", "session", "submit", "stats",
    "Session", "Scheduler", "ServeRejection", "Overloaded", "Degraded",
    "BackOff", "QueryFailed", "AdmissionSignals", "AdmissionController",
    "Decision", "current_session", "session_scope", "local_signals",
    "signals_from_health", "signals_from_metrics", "scheduler",
    "Subscription", "MAINTENANCE_SESSION",
]


def start(*, telemetry_port: Optional[int] = None) -> Scheduler:
    """Bring the serving layer up on the current (warm) runtime: start
    the scheduler's worker pool and — when a port is given — the
    telemetry HTTP endpoint the admission controller's remote twins
    scrape. Idempotent; returns the scheduler."""
    sched = scheduler()
    sched._ensure_workers()
    if telemetry_port is not None:
        from bodo_tpu.runtime import telemetry
        telemetry.serve(telemetry_port)
    return sched


def stop(*, drain_s: float = 0.0) -> None:
    """Stop the worker pool, optionally draining in-flight work first.
    Queued work survives and resumes on the next start()/submit."""
    sched = scheduler()
    if drain_s > 0:
        sched.drain(timeout=drain_s)
    sched.stop()


def drain(timeout: float = 30.0) -> bool:
    """Block until all queued/running queries finish (True) or the
    timeout expires (False)."""
    return scheduler().drain(timeout=timeout)


def session(session_id: Optional[str] = None, *, priority: float = 1.0,
            allow_degraded: bool = False,
            slo: str = "throughput") -> Session:
    """Open a logical session on the resident gang. ``priority`` is the
    fair-share weight (2.0 gets twice the gang of 1.0 under
    contention); ``allow_degraded`` opts into service while the gang
    has unhealthy ranks; ``slo`` is the service class — ``"latency"``
    ages serve_latency_boost× faster under contention,
    ``"throughput"`` (default) takes the plain fair share."""
    return scheduler().session(session_id, priority=priority,
                               allow_degraded=allow_degraded, slo=slo)


def submit(fn: Callable, session_id: str = "default"):
    """One-shot convenience: submit a thunk on a named (default)
    session; returns its Future."""
    return session(session_id).submit(fn)


def stats() -> dict:
    """Scheduler snapshot (sessions, queue depths, decision counters)."""
    return scheduler().stats()
